"""DeviceShare host-side manager: exact GPU slot (minor) assignment.

Rebuild of the reference plugin's control plane
(``pkg/scheduler/plugins/deviceshare/plugin.go:179-556``,
``device_cache.go``, ``device_allocator.go``): ingests per-node Device
inventories, lowers per-slot free state to the solver
(``ops.device.DeviceState``), and for each winner picks concrete device
minors — best-fit partial slot for fractional requests, fully-free slots
for whole-GPU requests — writing the
``scheduling.koordinator.sh/device-allocated`` annotation
(``plugin.go:556-630``).
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from ...api import extension as ext
from ...api.types import Device, Pod
from ...core.snapshot import ClusterSnapshot

FULL = 100.0


def parse_gpu_request(pod: Pod) -> Tuple[int, float]:
    """(whole_gpus, share_percent) — see api.extension.parse_gpu_request."""
    return ext.parse_gpu_request(pod.spec.requests)


@dataclasses.dataclass
class _NodeDevices:
    #: free MEMORY percent per GPU minor (the memory-ratio dimension —
    #: the authoritative "full minor" / solver-lowering view)
    gpu_free: List[float]
    #: free CORE percent per GPU minor, tracked INDEPENDENTLY (reference
    #: ``device_cache.go`` resource-vector accounting: a high-memory/
    #: low-core pod and a low-memory/high-core pod share one GPU)
    gpu_core_free: List[float] = dataclasses.field(default_factory=list)
    #: GPU memory capacity in bytes per minor (0 = not declared by the
    #: Device CR; byte-denominated requests then cannot be converted)
    gpu_mem_cap: List[float] = dataclasses.field(default_factory=list)
    #: free percent per RDMA minor (100 = idle NIC; VF-carrying NICs are
    #: shared VF-by-VF and never consumed whole)
    rdma_free: List[float] = dataclasses.field(default_factory=list)
    #: free percent per FPGA minor
    fpga_free: List[float] = dataclasses.field(default_factory=list)
    #: PCIe root per RDMA minor ("" unknown)
    rdma_pcie: List[str] = dataclasses.field(default_factory=list)
    #: NUMA node per RDMA minor (-1 unknown; topology-scope hints)
    rdma_numa: List[int] = dataclasses.field(default_factory=list)
    #: free SR-IOV virtual-function bus IDs per RDMA minor (empty list =
    #: the NIC exposes no VFs and is allocated whole)
    rdma_vfs: List[List[str]] = dataclasses.field(default_factory=list)
    #: full VF inventory per RDMA minor (distinguishes "no VFs" from
    #: "VFs exhausted"; restores on reset)
    rdma_vf_all: List[List[str]] = dataclasses.field(default_factory=list)
    #: GPU vendor from the Device CR's gpu-vendor label ("" = generic) —
    #: dispatches the device-plugin adapter (device_plugin_adapter.go)
    vendor: str = ""
    #: pod uid -> [(minor, mem_ratio_percent, core_percent)] of GPU picks
    owners: Dict[str, List[Tuple[int, float, float]]] = dataclasses.field(
        default_factory=dict
    )
    #: pod uid -> [(minor, percent, vf_bus_id|None)] of RDMA picks
    rdma_owners: Dict[str, List[Tuple[int, float, Optional[str]]]] = (
        dataclasses.field(default_factory=dict)
    )
    #: pod uid -> [(minor, percent)] of FPGA picks
    fpga_owners: Dict[str, List[Tuple[int, float]]] = dataclasses.field(
        default_factory=dict
    )
    #: size -> partitions (GPUPartitionTable); empty = no table
    partitions: Dict[int, List["GPUPartition"]] = dataclasses.field(
        default_factory=dict
    )
    #: "Honor" | "Prefer" | ""
    partition_policy: str = ""
    #: NUMA node per minor (topology fallback packing), -1 unknown
    numa_of: List[int] = dataclasses.field(default_factory=list)
    #: PCIe root per minor ("" unknown)
    pcie_of: List[str] = dataclasses.field(default_factory=list)
    #: static (numa, pcie) interconnect group id per minor — precomputed
    #: at ingest so the per-winner topology packing is plain list ops
    group_of: List[int] = dataclasses.field(default_factory=list)
    n_groups: int = 0
    #: lazily-built constant payload fragment per minor for whole-GPU
    #: allocations (shape is fixed per node; rebuilt when the node's
    #: Device CR is re-ingested since that replaces this object)
    whole_frags: Optional[List[str]] = None


#: machine models whose boards ship the NVLink-complete 1/2/4/8 partition
#: layout (reference ``allocator_gpu_helper.go:157`` model dispatch)
HOPPER_MODELS = ("H100", "H800", "H20")


def hopper_partition_table() -> Dict[int, List["GPUPartition"]]:
    """The canonical 8-GPU Hopper partition table (reference
    ``GPUPartitionIndexOfNVIDIAHopper``): singles, NVLink pairs
    (0,1)/(2,3)/(4,5)/(6,7), quads (0-3)/(4-7), and the full octet, all at
    allocation score 1."""
    from ...api.types import GPUPartition

    def parts(groups):
        return [GPUPartition(minors=list(g)) for g in groups]

    return {
        1: parts([[m] for m in range(8)]),
        2: parts([[0, 1], [2, 3], [4, 5], [6, 7]]),
        4: parts([[0, 1, 2, 3], [4, 5, 6, 7]]),
        8: parts([list(range(8))]),
    }


def partition_table_for_model(model: str) -> Dict[int, List["GPUPartition"]]:
    """Model-dispatched default table (``getGPUPartitionIndexer``); unknown
    models get no table (topology packing applies instead)."""
    if any(model.startswith(m) for m in HOPPER_MODELS):
        return hopper_partition_table()
    return {}


class DeviceManager:
    """Per-node device inventories + exact allocation (nodeDeviceCache)."""

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        max_gpus: int = 8,
        scoring_strategy: Optional[str] = None,
    ):
        self.snapshot = snapshot
        self.max_gpus = max_gpus
        #: "LeastAllocated" | "MostAllocated" | None — DeviceShare Score
        #: strategy (reference DeviceShareArgs.ScoringStrategy,
        #: deviceshare/scoring.go)
        self.scoring_strategy = scoring_strategy
        self._nodes: Dict[str, _NodeDevices] = {}
        #: incremental solver-lowering cache: rebuilding the [N, G] slot
        #: table + count vectors over every node each scheduling cycle
        #: was the latency stream's dominant fixed cost — rows refresh
        #: only for nodes whose inventory/allocations changed, and the
        #: whole cache drops on snapshot node churn (node_epoch)
        self._low: Optional[Dict[str, np.ndarray]] = None
        self._low_epoch: int = -1
        self._low_g: int = 0
        self._low_dirty: set = set()
        #: bumped whenever _lowered() actually changes the cached arrays
        #: (full rebuild or a dirty-row flush) — the scheduler keys its
        #: device-resident DeviceState upload off it
        self.lowered_version = 0
        #: snapshot row indices whose lowered rows changed since the last
        #: drain_lowered_dirty() — the scheduler scatters ONLY these into
        #: its device-resident DeviceState instead of re-uploading the
        #: whole [N, G] slot table (ROADMAP item b)
        self._scatter_rows: set = set()
        self._scatter_full = True
        #: widest GPU inventory ever ingested (monotone — shrink keeps
        #: harmless zero columns) so _lowered() needn't rescan every node
        self._max_minors: int = 0

    def _mark_dirty(self, node_name: str) -> None:
        if self._low is not None:
            self._low_dirty.add(node_name)

    def _refresh_row(self, name: str) -> None:
        """Recompute one node's row across all cached arrays."""
        low = self._low
        idx = self.snapshot.node_id(name)
        if idx is None:
            return
        st = self._nodes.get(name)
        if st is None:
            low["slots"][idx] = 0.0
            low["cap"][idx] = 0.0
            low["rdma"][idx] = 0.0
            low["fpga"][idx] = 0.0
            return
        row = np.zeros((low["slots"].shape[1],), np.float32)
        core_free = st.gpu_core_free
        for minor, free in enumerate(st.gpu_free):
            c = core_free[minor] if minor < len(core_free) else free
            row[minor] = free if free < c else c
        low["slots"][idx] = row
        low["cap"][idx] = len(st.gpu_free) * 100.0
        total = 0
        for i, f in enumerate(st.rdma_free):
            if i < len(st.rdma_vf_all) and st.rdma_vf_all[i]:
                total += len(st.rdma_vfs[i])
            elif f >= FULL - 1e-6:
                total += 1
        low["rdma"][idx] = total
        low["fpga"][idx] = sum(
            1 for f in st.fpga_free if f >= FULL - 1e-6
        )

    def _lowered(self) -> Dict[str, np.ndarray]:
        """The cached (slots, cap, rdma, fpga) arrays aligned to snapshot
        rows, refreshed incrementally. Callers must treat the returned
        arrays as read-only snapshots for immediate lowering (jnp.asarray
        / fancy indexing copy them onto the device)."""
        epoch = self.snapshot.node_epoch
        n_bucket = self.snapshot.nodes.allocatable.shape[0]
        g = max(self._max_minors or self.max_gpus, 1)
        if (
            self._low is None
            or self._low_epoch != epoch
            or g > self._low_g
            or self._low["slots"].shape[0] != n_bucket
        ):
            self._low = {
                "slots": np.zeros((n_bucket, g), np.float32),
                "cap": np.zeros((n_bucket,), np.float32),
                "rdma": np.zeros((n_bucket,), np.float32),
                "fpga": np.zeros((n_bucket,), np.float32),
            }
            self._low_epoch = epoch
            self._low_g = g
            self._low_dirty = set()
            for name in self._nodes:
                self._refresh_row(name)
            self.lowered_version += 1
            self._scatter_full = True
            self._scatter_rows.clear()
        elif self._low_dirty:
            for name in self._low_dirty:
                self._refresh_row(name)
                idx = self.snapshot.node_id(name)
                if idx is not None:
                    self._scatter_rows.add(int(idx))
            self._low_dirty = set()
            self.lowered_version += 1
        return self._low

    def drain_lowered_dirty(self) -> Optional[np.ndarray]:
        """Snapshot row indices whose lowered device rows changed since
        the last drain, or None for a full rebuild (see
        :func:`..plugins.drain_scatter_marks`). Call AFTER
        :meth:`_lowered` / ``slot_array`` (which flush pending dirty
        names)."""
        from . import drain_scatter_marks

        return drain_scatter_marks(self)

    def touch_lowered_rows(self, rows) -> None:
        """Mark lowered rows stale for the resident mirror WITHOUT a
        host-side change (anti-entropy scrubber heal path): the next
        resident refresh re-scatters host truth into exactly these
        rows."""
        self._scatter_rows.update(int(r) for r in rows)
        self.lowered_version += 1

    def upsert_device(self, device: Device) -> None:
        """Ingest/refresh a node's inventory. Live allocations survive a
        re-sync: the slot table is rebuilt from capacity and every owner's
        picks are re-applied (the reference nodeDeviceCache reconciles
        allocations from pod annotations the same way)."""
        gpus = [d for d in device.devices if d.dev_type == "gpu"]
        rdma = [d for d in device.devices if d.dev_type == "rdma"]
        fpga = [d for d in device.devices if d.dev_type == "fpga"]
        # partition table resolution (reference GetGPUPartitionTable →
        # getGPUPartitionIndexer): explicit field, else the Device CR's
        # gpu-partitions annotation, else the model-dispatched default
        partitions = dict(device.partitions)
        if not partitions:
            partitions = ext.parse_gpu_partition_table(device.meta.annotations)
        if not partitions:
            model = device.meta.labels.get(ext.LABEL_GPU_MODEL, "")
            if model:
                partitions = partition_table_for_model(model)
        policy = device.partition_policy or (
            ext.gpu_partition_policy(device.meta.labels)
            if partitions
            else ""
        )
        old = self._nodes.get(device.meta.name)
        st = _NodeDevices(
            gpu_free=[FULL] * len(gpus),
            gpu_core_free=[FULL] * len(gpus),
            gpu_mem_cap=[
                float(d.resources.get(ext.RES_GPU_MEMORY, 0.0)) for d in gpus
            ],
            rdma_free=[FULL] * len(rdma),
            rdma_pcie=[d.pcie_bus for d in rdma],
            rdma_numa=[d.numa_node for d in rdma],
            rdma_vfs=[list(d.vfs) for d in rdma],
            rdma_vf_all=[list(d.vfs) for d in rdma],
            fpga_free=[FULL] * len(fpga),
            partitions=partitions,
            partition_policy=policy,
            numa_of=[d.numa_node for d in gpus],
            pcie_of=[d.pcie_bus for d in gpus],
            vendor=device.meta.labels.get(ext.LABEL_GPU_VENDOR, ""),
        )
        gids: Dict[Tuple[int, str], int] = {}
        for d in gpus:
            key = (d.numa_node, d.pcie_bus)
            st.group_of.append(gids.setdefault(key, len(gids)))
        st.n_groups = len(gids)
        if old is not None:
            for uid, picks in old.owners.items():
                kept = [p for p in picks if p[0] < len(st.gpu_free)]
                for minor, pct, core in kept:
                    st.gpu_free[minor] = max(st.gpu_free[minor] - pct, 0.0)
                    st.gpu_core_free[minor] = max(
                        st.gpu_core_free[minor] - core, 0.0
                    )
                if kept:
                    st.owners[uid] = kept
            for uid, picks in old.rdma_owners.items():
                kept = [p for p in picks if p[0] < len(st.rdma_free)]
                for minor, pct, vf in kept:
                    if vf is not None and minor < len(st.rdma_vfs):
                        if vf in st.rdma_vfs[minor]:
                            st.rdma_vfs[minor].remove(vf)
                    else:
                        st.rdma_free[minor] = max(
                            st.rdma_free[minor] - pct, 0.0
                        )
                if kept:
                    st.rdma_owners[uid] = kept
            for uid, picks in old.fpga_owners.items():
                kept = [(m, pct) for m, pct in picks if m < len(st.fpga_free)]
                for minor, pct in kept:
                    st.fpga_free[minor] = max(st.fpga_free[minor] - pct, 0.0)
                if kept:
                    st.fpga_owners[uid] = kept
        self._nodes[device.meta.name] = st
        if len(st.gpu_free) > self._max_minors:
            self._max_minors = len(st.gpu_free)
        if self._low is not None and len(st.gpu_free) > self._low_g:
            self._low = None  # wider inventory: slot table must regrow
        else:
            self._mark_dirty(device.meta.name)

    def node(self, name: str) -> Optional[_NodeDevices]:
        return self._nodes.get(name)

    def remove_device(self, node_name: str) -> None:
        """Drop a node's device inventory (Device CR deleted / node gone);
        held allocations die with it — owners release via pod lifecycle."""
        self._nodes.pop(node_name, None)
        self._mark_dirty(node_name)

    @property
    def has_devices(self) -> bool:
        return bool(self._nodes)

    @property
    def has_rdma(self) -> bool:
        """Whether ANY node carries RDMA NICs — lets the solver trace the
        RDMA feasibility/carry out entirely on GPU-only clusters."""
        return any(st.rdma_free for st in self._nodes.values())

    @property
    def has_fpga(self) -> bool:
        return any(st.fpga_free for st in self._nodes.values())

    # ---- solver lowering ----

    def slot_array(self) -> np.ndarray:
        """slot_free [N, G] aligned to snapshot rows (ops.device.DeviceState).
        G grows with the largest node inventory — no silent truncation.
        Per-slot value is min(memory%, core%) free: the solver's share
        check must hold on BOTH dims; the host allocator revalidates
        exactly per dim. Incrementally cached (see ``_lowered``)."""
        return self._lowered()["slots"]

    def cap_array(self) -> np.ndarray:
        """Total GPU percent-units per node, [N] aligned to snapshot rows."""
        return self._lowered()["cap"]

    def rdma_array(self) -> np.ndarray:
        """Free RDMA allocation capacity per node, [N] aligned to snapshot
        rows: a VF-carrying NIC contributes its free VF count (it hosts
        one pod per VF), a plain NIC contributes 1 while idle."""
        return self._lowered()["rdma"]

    def fpga_array(self) -> np.ndarray:
        """Free FPGA count per node, [N] aligned to snapshot rows."""
        return self._lowered()["fpga"]

    # ---- device-plugin adapter (PreBind annotations) ----

    def adapter_annotations(
        self, node_name: str, uid: str, now: Optional[float] = None
    ) -> Dict[str, str]:
        """Vendor device-plugin protocol annotations for a device winner
        (reference ``device_plugin_adapter.go``): the bind timestamp in
        unix nanos always (plugins can't read pod manifests from kubelet
        and disambiguate same-node pods by it); the allocated GPU minors
        as a comma list; and for Huawei-vendor inventories the NPU
        plugin's ``predicate-time`` + ``huawei.com/npu-core`` pair (the
        full-NPU path — this rebuild carries no vNPU shared-resource
        templates)."""
        # time_ns: float seconds would quantize at ~µs and collide for
        # same-round winners, which plugins disambiguate by this value
        ts = str(
            int(now * 1e9) if now is not None else _time.time_ns()
        )
        out = {ext.ANNOTATION_BIND_TIMESTAMP: ts}
        st = self._nodes.get(node_name)
        if st is None:
            return out
        picks = st.owners.get(uid)
        if picks:
            minors = ",".join(str(m) for m, _pct, _core in picks)
            out[ext.ANNOTATION_GPU_MINORS] = minors
            if st.vendor == ext.GPU_VENDOR_HUAWEI:
                out[ext.ANNOTATION_PREDICATE_TIME] = ts
                out[ext.ANNOTATION_HUAWEI_NPU_CORE] = minors
        return out

    # ---- exact assignment (Reserve/PreBind) ----

    def allocate(self, pod: Pod, node_name: str) -> Optional[Mapping[str, str]]:
        """Pick concrete minors for the winner; None = failed Reserve.

        GPU and RDMA are allocated jointly: with the joint-allocate
        annotation (``device_allocator.go:205-252`` tryJointAllocate), the
        GPU picks' PCIe roots steer the RDMA picks — preferred by default,
        binding under the SamePCIe required scope (the RDMA PCIe set must
        equal the GPU PCIe set, ``validateJointAllocation``)."""
        whole, share = parse_gpu_request(pod)
        payload = self.allocate_lowered(
            pod.meta.uid,
            pod.meta.annotations,
            node_name,
            whole,
            share,
            ext.parse_rdma_request(pod.spec.requests),
            ext.parse_fpga_request(pod.spec.requests),
            requests=pod.spec.requests,
        )
        if payload is None:
            return None
        if not payload:
            return {}
        patch = {ext.ANNOTATION_DEVICE_ALLOCATED: payload}
        patch.update(self.adapter_annotations(node_name, pod.meta.uid))
        return patch

    def allocate_lowered(
        self,
        uid: str,
        annotations: Mapping[str, str],
        node_name: str,
        whole: int,
        share: float,
        rdma_count: int,
        fpga_count: int,
        requests: Optional[Mapping[str, float]] = None,
    ) -> Optional[str]:
        """Lean core of ``allocate`` for the batched commit: requests are
        pre-lowered by the caller. Returns the device-allocated JSON
        payload, ``""`` when the pod wants no devices, None on failure.

        With ``requests``, the GPU demand is re-derived as an independent
        (core%, memory) vector (:func:`ext.parse_gpu_request_vector`) so a
        high-memory/low-core pod and a low-memory/high-core pod can share
        one GPU; without it the scalar ``share`` charges both dims
        equally (conservative)."""
        if whole == 0 and share <= 0 and rdma_count == 0 and fpga_count == 0:
            return ""
        st = self._nodes.get(node_name)
        if st is None:
            return None
        if requests is not None and (
            ext.RES_GPU_CORE in requests
            or ext.RES_GPU_MEMORY in requests
            or ext.RES_GPU_MEMORY_RATIO in requests
            or ext.RES_KOORD_GPU in requests
        ):
            whole, core, ratio, mem_bytes = ext.parse_gpu_request_vector(
                requests
            )
        else:
            # whole-GPU-only request: the lowered scalars already say it
            # all — skip the per-dim re-parse (commit hot path)
            core, ratio, mem_bytes = share, share, None
        picks: List[Tuple[int, float, float]] = []
        free = list(st.gpu_free)
        core_free = list(st.gpu_core_free)
        full_minors = [
            i
            for i, f in enumerate(free)
            if f >= FULL - 1e-6 and core_free[i] >= FULL - 1e-6
        ]
        if len(full_minors) < whole:
            return None
        if whole > 0:
            chosen = self._pick_whole_minors(
                st, full_minors, whole, annotations
            )
            if chosen is None:
                return None
            for minor in chosen:
                picks.append((minor, FULL, FULL))
                free[minor] = 0.0
                core_free[minor] = 0.0
        if core > 0 or ratio > 0 or mem_bytes is not None:
            # per-minor memory need in ratio percent: byte-denominated
            # requests convert via the minor's declared capacity
            # (device_cache.go converts memory<->ratio the same way)
            caps = st.gpu_mem_cap

            def mem_need(i: int) -> Optional[float]:
                if mem_bytes is None:
                    return ratio
                cap = caps[i] if i < len(caps) else 0.0
                if cap <= 0:
                    return None  # capacity undeclared: cannot account
                return mem_bytes / cap * 100.0

            # best-fit: tightest partial slot where BOTH dims fit, else a
            # fresh full slot (reference allocator_gpu.go scoring)
            best = None
            for i, f in enumerate(free):
                if f >= FULL - 1e-6 and core_free[i] >= FULL - 1e-6:
                    continue  # fully-free slots are the fallback
                need = mem_need(i)
                if need is None or f < need - 1e-6:
                    continue
                if core_free[i] < core - 1e-6:
                    continue
                if best is None or f < best[0]:
                    best = (f, i)
            if best is not None:
                minor = best[1]
            else:
                minor = next(
                    (
                        i
                        for i, f in enumerate(free)
                        if f >= FULL - 1e-6
                        and core_free[i] >= FULL - 1e-6
                        and mem_need(i) is not None
                        and mem_need(i) <= FULL + 1e-6
                    ),
                    None,
                )
                if minor is None:
                    return None
            need = mem_need(minor)
            picks.append((minor, need, core))
            free[minor] -= need
            core_free[minor] -= core
        # per-type allocation hints (device_share.go:147-190): the RDMA
        # strategy may rewrite the count, and a required topology scope
        # constrains which NICs may be grouped
        hints = ext.parse_device_allocate_hints(annotations)
        rdma_hint = hints.get("rdma", {})
        strategy = rdma_hint.get("allocateStrategy", "")
        if (
            strategy == ext.DEVICE_ALLOCATE_STRATEGY_REQUESTS_AS_COUNT
            and requests is not None
        ):
            # the raw request value IS the device count (not 100-units)
            try:
                rdma_count = int(float(requests.get(ext.RES_RDMA, 0.0)))
            except (TypeError, ValueError):
                pass
        elif strategy == ext.DEVICE_ALLOCATE_STRATEGY_APPLY_FOR_ALL:
            # one allocation on EVERY rdma device of the node (the
            # machine-wide NIC pattern for distributed training pods)
            rdma_count = max(rdma_count, len(st.rdma_free))
        rdma_picks: List[Tuple[int, float, Optional[str]]] = []
        if rdma_count > 0:
            gpu_pcies = {
                st.pcie_of[p[0]] for p in picks if p[0] < len(st.pcie_of)
            }
            chosen_rdma = self._pick_rdma(
                st,
                rdma_count,
                ext.parse_device_joint_allocate(annotations),
                gpu_pcies,
                topology_scope=rdma_hint.get("requiredTopologyScope", ""),
            )
            if chosen_rdma is None:
                return None
            for m in chosen_rdma:
                if st.rdma_vf_all[m] if m < len(st.rdma_vf_all) else False:
                    # VF-carrying NIC: hand out one VF, never the whole
                    # NIC (SR-IOV sharing, device_share.go:126-139)
                    if not st.rdma_vfs[m]:
                        return None
                    rdma_picks.append((m, FULL, st.rdma_vfs[m][0]))
                else:
                    rdma_picks.append((m, FULL, None))
        fpga_picks: List[Tuple[int, float]] = []
        if fpga_count > 0:
            free_fpga = [
                i for i, f in enumerate(st.fpga_free) if f >= FULL - 1e-6
            ]
            if len(free_fpga) < fpga_count:
                return None
            fpga_picks = [(m, FULL) for m in free_fpga[:fpga_count]]
        # all picks succeeded — commit atomically
        st.gpu_free = free
        st.gpu_core_free = core_free
        if picks:
            st.owners[uid] = picks
        for minor, pct, vf in rdma_picks:
            if vf is not None:
                st.rdma_vfs[minor].remove(vf)
            else:
                st.rdma_free[minor] = max(st.rdma_free[minor] - pct, 0.0)
        if rdma_picks:
            st.rdma_owners[uid] = rdma_picks
        for minor, pct in fpga_picks:
            st.fpga_free[minor] = max(st.fpga_free[minor] - pct, 0.0)
        if fpga_picks:
            st.fpga_owners[uid] = fpga_picks
        self._mark_dirty(node_name)
        # hand-rendered device-allocated JSON (shape is fixed; json.dumps
        # per winner was a visible slice of the commit hot path). GPU
        # entries carry the full per-dim vector (gpu-core / memory-ratio /
        # memory bytes when capacity is declared); RDMA entries carry the
        # assigned VF in the reference's DeviceAllocationExtension shape.
        parts: List[str] = []
        if picks:
            gpu_items = []
            for minor, pct, core_pct in picks:
                res = '"%s": %s, "%s": %s' % (
                    ext.RES_GPU_CORE,
                    core_pct,
                    ext.RES_GPU_MEMORY_RATIO,
                    pct,
                )
                cap = (
                    st.gpu_mem_cap[minor]
                    if minor < len(st.gpu_mem_cap)
                    else 0.0
                )
                if cap > 0:
                    res += ', "%s": %d' % (
                        ext.RES_GPU_MEMORY,
                        int(pct / 100.0 * cap),
                    )
                gpu_items.append(
                    '{"minor": %d, "resources": {%s}}' % (minor, res)
                )
            parts.append('"gpu": [%s]' % ", ".join(gpu_items))
        if rdma_picks:
            rdma_items = []
            for minor, pct, vf in rdma_picks:
                if vf is not None:
                    rdma_items.append(
                        '{"minor": %d, "resources": {"%s": %s}, '
                        '"extension": {"vfs": [{"busID": "%s"}]}}'
                        % (minor, ext.RES_RDMA, pct, vf)
                    )
                else:
                    rdma_items.append(
                        '{"minor": %d, "resources": {"%s": %s}}'
                        % (minor, ext.RES_RDMA, pct)
                    )
            parts.append('"rdma": [%s]' % ", ".join(rdma_items))
        if fpga_picks:
            parts.append(
                '"fpga": [%s]'
                % ", ".join(
                    '{"minor": %d, "resources": {"%s": %s}}'
                    % (minor, ext.RES_FPGA, pct)
                    for minor, pct in fpga_picks
                )
            )
        return "{%s}" % ", ".join(parts)

    def allocate_batch(
        self,
        uids: List[str],
        annotations: List[Mapping[str, str]],
        node_names: List[str],
        whole_l: List[int],
        share_l: List[float],
        rdma_l: List[int],
        fpga_l: List[int],
        requests_l: List[Optional[Mapping[str, float]]],
    ) -> List[Optional[str]]:
        """Batched :meth:`allocate_lowered` over one chunk's winners in
        commit order (VERDICT r3 #1: the per-winner device loop was the
        device-gang scenario's host wall). Winners are grouped by node;
        whole-GPU-only requests with no device annotations take a lean
        inline path (full-minor scan → topology-group pick → in-place
        charge → pre-rendered payload fragments); anything else falls
        back to :meth:`allocate_lowered` with identical semantics."""
        n = len(uids)
        results: List[Optional[str]] = [""] * n
        by_node: Dict[str, List[int]] = {}
        for i, name in enumerate(node_names):
            lst = by_node.get(name)
            if lst is None:
                by_node[name] = [i]
            else:
                lst.append(i)
        hint_key = ext.ANNOTATION_DEVICE_ALLOCATE_HINT
        joint_key = ext.ANNOTATION_DEVICE_JOINT_ALLOCATE
        part_key = ext.ANNOTATION_GPU_PARTITION_SPEC
        full_eps = FULL - 1e-6
        for name, rows_i in by_node.items():
            st = self._nodes.get(name)
            if st is None:
                for i in rows_i:
                    if (
                        whole_l[i] > 0
                        or share_l[i] > 0
                        or rdma_l[i] > 0
                        or fpga_l[i] > 0
                    ):
                        results[i] = None
                continue
            partitioned = bool(st.partitions) and st.partition_policy in (
                "Honor",
                "Prefer",
            )
            gpu_free = st.gpu_free
            core_free = st.gpu_core_free
            n_minors = len(gpu_free)
            owners = st.owners
            frags = st.whole_frags
            if frags is None:
                caps = st.gpu_mem_cap
                frags = []
                for m in range(n_minors):
                    res = '"%s": %s, "%s": %s' % (
                        ext.RES_GPU_CORE,
                        FULL,
                        ext.RES_GPU_MEMORY_RATIO,
                        FULL,
                    )
                    cap = caps[m] if m < len(caps) else 0.0
                    if cap > 0:
                        res += ', "%s": %d' % (ext.RES_GPU_MEMORY, int(cap))
                    frags.append('{"minor": %d, "resources": {%s}}' % (m, res))
                st.whole_frags = frags
            # free minors bucketed by topology group ONCE per node, drained
            # across this node's winners (rebuilding the full-minor list per
            # winner was the remaining per-pod scan in the lean path)
            group_of = st.group_of
            n_groups = max(st.n_groups, 1)
            by_group: Optional[List[List[int]]] = None
            for i in rows_i:
                whole = whole_l[i]
                ann = annotations[i]
                req = requests_l[i]
                if (
                    whole > 0
                    and share_l[i] <= 0
                    and rdma_l[i] == 0
                    and fpga_l[i] == 0
                    and not partitioned
                    and hint_key not in ann
                    and joint_key not in ann
                    and part_key not in ann
                    and (
                        req is None
                        or (
                            ext.RES_GPU_CORE not in req
                            and ext.RES_GPU_MEMORY not in req
                            and ext.RES_GPU_MEMORY_RATIO not in req
                            and ext.RES_KOORD_GPU not in req
                        )
                    )
                ):
                    if by_group is None:
                        by_group = [[] for _ in range(n_groups)]
                        for m in range(n_minors):
                            if gpu_free[m] >= full_eps and core_free[m] >= full_eps:
                                by_group[
                                    group_of[m] if m < len(group_of) else 0
                                ].append(m)
                    chosen = self._pick_grouped_free(by_group, whole)
                    if chosen is None:
                        results[i] = None
                        continue
                    for m in chosen:
                        gpu_free[m] = 0.0
                        core_free[m] = 0.0
                    owners[uids[i]] = [(m, FULL, FULL) for m in chosen]
                    self._mark_dirty(name)
                    results[i] = '{"gpu": [%s]}' % ", ".join(
                        frags[m] for m in chosen
                    )
                elif (
                    whole == 0
                    and share_l[i] <= 0
                    and rdma_l[i] == 0
                    and fpga_l[i] == 0
                ):
                    continue  # no device demand: results stays ""
                else:
                    results[i] = self.allocate_lowered(
                        uids[i],
                        ann,
                        name,
                        whole,
                        share_l[i],
                        rdma_l[i],
                        fpga_l[i],
                        requests=req,
                    )
                    # allocate_lowered commits by REBINDING st.gpu_free /
                    # st.gpu_core_free to fresh lists — re-hoist or the
                    # lean path keeps mutating the orphaned old lists
                    # (double-allocating minors and losing charges)
                    gpu_free = st.gpu_free
                    core_free = st.gpu_core_free
                    by_group = None  # free set changed: rebuild lazily
        return results

    def _pick_rdma(
        self,
        st: _NodeDevices,
        count: int,
        joint: "Optional[Tuple[Tuple[str, ...], str]]",
        gpu_pcies: set,
        topology_scope: str = "",
    ) -> Optional[List[int]]:
        """Choose RDMA minors. Joint allocation with GPUs prefers NICs on
        the GPUs' PCIe roots; the SamePCIe scope requires the chosen NICs'
        PCIe set to exactly equal the GPUs' (one per root, count bumped to
        the root count like the reference's desiredCount adjustment).
        A VF-carrying NIC is available while it has a free VF (it is
        shared, never consumed whole); a plain NIC while idle.
        ``topology_scope`` (DeviceHint.RequiredTopologyScope): "PCIe" /
        "NUMANode" restricts the chosen set to NICs sharing that scope."""
        free_minors = [
            i
            for i in range(len(st.rdma_free))
            if (
                bool(st.rdma_vfs[i])
                if i < len(st.rdma_vf_all) and st.rdma_vf_all[i]
                else st.rdma_free[i] >= FULL - 1e-6
            )
        ]
        if topology_scope in ("PCIe", "NUMANode"):
            def scope_key(m: int):
                if topology_scope == "PCIe":
                    return st.rdma_pcie[m] if m < len(st.rdma_pcie) else ""
                return st.rdma_numa[m] if m < len(st.rdma_numa) else -1

            groups: Dict[object, List[int]] = {}
            for m in free_minors:
                groups.setdefault(scope_key(m), []).append(m)
            fitting = [g for g in groups.values() if len(g) >= count]
            if not fitting:
                return None
            # tightest fitting scope group (least leftover)
            free_minors = min(fitting, key=len)
        if len(free_minors) < count:
            return None
        joint_with_gpu = (
            joint is not None and "rdma" in joint[0] and bool(gpu_pcies)
        )
        if not joint_with_gpu:
            return free_minors[:count]
        scope = joint[1]
        in_pcie = [m for m in free_minors if st.rdma_pcie[m] in gpu_pcies]
        if scope == "SamePCIe":
            per_pcie: Dict[str, List[int]] = {}
            for m in in_pcie:
                per_pcie.setdefault(st.rdma_pcie[m], []).append(m)
            if set(per_pcie) != gpu_pcies:
                return None  # some GPU PCIe root has no free NIC
            need = max(count, len(gpu_pcies))
            chosen = [per_pcie[p][0] for p in sorted(per_pcie)]
            extras = [m for p in sorted(per_pcie) for m in per_pcie[p][1:]]
            for m in extras:
                if len(chosen) >= need:
                    break
                chosen.append(m)
            return chosen if len(chosen) >= need else None
        ordered = in_pcie + [m for m in free_minors if m not in set(in_pcie)]
        return ordered[:count]

    # ---- whole-GPU selection: partition table + topology packing ----
    # Rebuild of the reference's partition allocator
    # (``allocator_gpu.go:177-299`` allocateByPartition /
    # selectPartitionByBinPack): multi-GPU allocations land inside one
    # interconnect-complete partition; among feasible partitions, prefer
    # the one that keeps the most high-value larger partitions intact.

    def _pick_whole_minors(
        self,
        st: _NodeDevices,
        full_minors: List[int],
        whole: int,
        annotations: Mapping[str, str],
    ) -> Optional[List[int]]:
        """``full_minors``: minors fully free on every dimension (the
        caller computes them over both memory and core)."""
        if st.partitions and st.partition_policy in ("Honor", "Prefer"):
            chosen = self._allocate_by_partition(
                st, full_minors, whole, annotations
            )
            if chosen is not None:
                return chosen
            if st.partition_policy == "Honor":
                # table is binding: no feasible partition = failed Reserve
                # (ErrInsufficientPartitionedDevice / unsupported size)
                return None
        return self._allocate_by_topology(st, full_minors, whole)

    def _allocate_by_partition(
        self,
        st: _NodeDevices,
        full_minors: List[int],
        whole: int,
        annotations: Mapping[str, str],
    ) -> Optional[List[int]]:
        table = st.partitions.get(whole)
        if not table:
            return None
        restricted, want_bw = ext.parse_gpu_partition_spec(annotations)
        free_mask = 0
        for m in full_minors:
            free_mask |= 1 << m
        # walk allocation-score tiers best-first; Restricted pods may only
        # use the best tier, BestEffort walks down until one is feasible
        tiers: Dict[int, List] = {}
        for part in table:
            tiers.setdefault(part.allocation_score, []).append(part)
        feasible = []
        for score in sorted(tiers, reverse=True):
            for part in tiers[score]:
                if part.minors_mask & ~free_mask:
                    continue    # some minor busy or absent
                if want_bw > 0 and part.ring_bus_bandwidth < want_bw:
                    continue
                feasible.append(part)
            if feasible or restricted:
                break
        if not feasible:
            return None
        if len(feasible) == 1:
            return list(feasible[0].minors)
        return list(self._bin_pack_partition(st, free_mask, feasible, whole).minors)

    def _bin_pack_partition(self, st, free_mask: int, feasible, whole: int):
        """Choose the partition whose allocation preserves the most intact
        larger partitions, weighted steeply by size (reference
        selectPartitionByBinPack weights {8: 10000, 4: 100, 2: 1})."""
        weight = {8: 10_000, 4: 100, 2: 1}

        def preserve_score(candidate) -> int:
            after_busy = ~free_mask | candidate.minors_mask
            score = 0
            for size, parts in st.partitions.items():
                if size < whole or size not in weight:
                    continue
                for part in parts:
                    if part.minors_mask & after_busy:
                        continue
                    score += weight[size] * part.allocation_score
            return score

        return max(feasible, key=preserve_score)

    @staticmethod
    def _pick_grouped_free(
        by_group: List[List[int]], whole: int
    ) -> Optional[List[int]]:
        """Tightest-group whole-GPU pick over live free-minor buckets,
        DRAINING the chosen minors in place (same policy as
        :meth:`_allocate_by_topology`: smallest satisfying NUMA/PCIe
        group, else spill across groups largest-first)."""
        if len(by_group) == 1:
            b = by_group[0]
            if len(b) < whole:
                return None
            chosen = b[:whole]
            del b[:whole]
            return chosen
        best: Optional[List[int]] = None
        for b in by_group:
            if len(b) >= whole and (best is None or len(b) < len(best)):
                best = b
        if best is not None:
            chosen = best[:whole]
            del best[:whole]
            return chosen
        if sum(len(b) for b in by_group) < whole:
            return None
        out: List[int] = []
        for g in sorted(by_group, key=len, reverse=True):
            need = whole - len(out)
            if need <= 0:
                break
            out.extend(g[:need])
            del g[:need]
        return out

    def _allocate_by_topology(
        self, st: _NodeDevices, full_minors: List[int], whole: int
    ) -> Optional[List[int]]:
        """No (binding) partition table: pack onto the fewest NUMA/PCIe
        domains, preferring the domain group with least leftover (the
        reference's GPUTopologyScope bin-pack, ``allocator_gpu.go:300+``).
        Group membership is static per node (``group_of``, precomputed at
        ingest), so the per-winner work is plain list bucketing."""
        if len(full_minors) < whole:
            return None
        if st.n_groups <= 1:
            return full_minors[:whole]
        group_of = st.group_of
        buckets: List[List[int]] = [[] for _ in range(st.n_groups)]
        for m in full_minors:
            buckets[group_of[m] if m < len(group_of) else 0].append(m)
        # smallest group that satisfies the request = tightest fit
        best: Optional[List[int]] = None
        for b in buckets:
            if len(b) >= whole and (best is None or len(b) < len(best)):
                best = b
        if best is not None:
            return best[:whole]
        # spill across groups, draining the largest first
        ordered = sorted(buckets, key=len, reverse=True)
        out: List[int] = []
        for g in ordered:
            out.extend(g)
            if len(out) >= whole:
                return out[:whole]
        return None

    def reset_allocations(self) -> None:
        """Free every slot and drop all owners (full-resync path)."""
        for st in self._nodes.values():
            st.gpu_free = [FULL] * len(st.gpu_free)
            st.gpu_core_free = [FULL] * len(st.gpu_core_free)
            st.rdma_free = [FULL] * len(st.rdma_free)
            st.rdma_vfs = [list(v) for v in st.rdma_vf_all]
            st.fpga_free = [FULL] * len(st.fpga_free)
            st.owners.clear()
            st.rdma_owners.clear()
            st.fpga_owners.clear()
        self._low = None

    def release(self, pod_uid: str, node_name: str) -> None:
        st = self._nodes.get(node_name)
        if st is None:
            return
        self._mark_dirty(node_name)
        for minor, pct, core in st.owners.pop(pod_uid, []):
            st.gpu_free[minor] = min(st.gpu_free[minor] + pct, FULL)
            st.gpu_core_free[minor] = min(
                st.gpu_core_free[minor] + core, FULL
            )
        for minor, pct, vf in st.rdma_owners.pop(pod_uid, []):
            if vf is not None:
                if vf not in st.rdma_vfs[minor]:
                    st.rdma_vfs[minor].append(vf)
            else:
                st.rdma_free[minor] = min(st.rdma_free[minor] + pct, FULL)
        for minor, pct in st.fpga_owners.pop(pod_uid, []):
            st.fpga_free[minor] = min(st.fpga_free[minor] + pct, FULL)

    # ---- exact-hold journal coverage (HA PR 6 satellite) ----

    def hold_of(self, pod_uid: str, node_name: str) -> Optional[dict]:
        """JSON-serializable snapshot of the pod's exact device hold —
        concrete GPU minors (+share/core pct), RDMA minors (+VF), FPGA
        minors — for the write-ahead bind journal, so a takeover
        restores the EXACT slots via :meth:`restore_hold` instead of
        re-picking (a re-pick could legally choose different minors and
        silently diverge from the annotations the kubelet already
        acted on)."""
        st = self._nodes.get(node_name)
        if st is None:
            return None
        gpu = st.owners.get(pod_uid)
        rdma = st.rdma_owners.get(pod_uid)
        fpga = st.fpga_owners.get(pod_uid)
        if not gpu and not rdma and not fpga:
            return None
        hold: dict = {}
        if gpu:
            hold["gpu"] = [[int(m), float(p), float(c)] for m, p, c in gpu]
        if rdma:
            hold["rdma"] = [[int(m), float(p), vf] for m, p, vf in rdma]
        if fpga:
            hold["fpga"] = [[int(m), float(p)] for m, p in fpga]
        return hold

    def restore_hold(self, pod_uid: str, node_name: str, hold: dict) -> None:
        """Re-install a journaled device hold on a recovering instance
        (idempotent: a pod already owning slots on this node is left
        alone — the statehub resync may have re-registered it first)."""
        st = self._nodes.get(node_name)
        if st is None:
            return
        if (
            pod_uid in st.owners
            or pod_uid in st.rdma_owners
            or pod_uid in st.fpga_owners
        ):
            return
        self._mark_dirty(node_name)
        gpu = [
            (int(m), float(p), float(c))
            for m, p, c in hold.get("gpu", ())
            if int(m) < len(st.gpu_free)
        ]
        if gpu:
            for m, p, c in gpu:
                st.gpu_free[m] = max(st.gpu_free[m] - p, 0.0)
                st.gpu_core_free[m] = max(st.gpu_core_free[m] - c, 0.0)
            st.owners[pod_uid] = gpu
        rdma = [
            (int(m), float(p), vf)
            for m, p, vf in hold.get("rdma", ())
            if int(m) < len(st.rdma_free)
        ]
        if rdma:
            for m, p, vf in rdma:
                if vf is not None:
                    try:
                        st.rdma_vfs[m].remove(vf)
                    except ValueError:
                        pass
                else:
                    st.rdma_free[m] = max(st.rdma_free[m] - p, 0.0)
            st.rdma_owners[pod_uid] = rdma
        fpga = [
            (int(m), float(p))
            for m, p in hold.get("fpga", ())
            if int(m) < len(st.fpga_free)
        ]
        if fpga:
            for m, p in fpga:
                st.fpga_free[m] = max(st.fpga_free[m] - p, 0.0)
            st.fpga_owners[pod_uid] = fpga
