"""Scheduler plugin managers (coscheduling / elasticquota / NUMA /
deviceshare / reservation / preemption) plus small helpers they share."""

from typing import Optional

import numpy as np


def drain_scatter_marks(mgr) -> Optional[np.ndarray]:
    """Shared ``drain_lowered_dirty`` body for managers that maintain a
    device-resident lowered table (NUMAManager / DeviceManager): consume
    ``mgr._scatter_rows`` / ``mgr._scatter_full`` and return the sorted
    snapshot row indices whose lowered rows changed since the last drain,
    or None when the resident mirror must re-upload the whole table
    (full rebuild). SINGLE-CONSUMER, like ``ClusterSnapshot.drain_dirty``
    — the scheduler's resident state is the one drainer."""
    if mgr._scatter_full:
        mgr._scatter_full = False
        mgr._scatter_rows.clear()
        return None
    rows = np.fromiter(
        mgr._scatter_rows, np.int32, count=len(mgr._scatter_rows)
    )
    rows.sort()
    mgr._scatter_rows.clear()
    return rows
