"""koord-descheduler binary (reference ``cmd/koord-descheduler/``):
LowNodeLoad balancing over the utilization snapshot, leader-elected."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..descheduler.framework import Descheduler, Profile
from ..descheduler.low_node_load import (
    LowNodeLoad,
    LowNodeLoadArgs,
    LowNodeLoadBalance,
)
from . import _common


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-descheduler")
    _common.add_common_flags(parser)
    _common.add_sim_flags(parser)
    parser.add_argument("--low-threshold", type=float, default=45.0)
    parser.add_argument("--high-threshold", type=float, default=70.0)
    parser.add_argument("--dry-run", action="store_true")
    parser.add_argument("--max-evictions-per-round", type=int, default=0)
    parser.add_argument(
        "--config",
        default="",
        help="LowNodeLoad plugin-args JSON (thresholds, nodePools, "
        "resourceWeights, nodeFit)",
    )
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    snap, nodes, pods, _hub = _common.build_snapshot(args)

    la = LowNodeLoadArgs(
        low_thresholds={"cpu": args.low_threshold},
        high_thresholds={"cpu": args.high_threshold},
    )
    pools = []
    if getattr(args, "config", None):
        import json

        from ..scheduler.config import (
            decode_low_node_load,
            decode_low_node_load_pools,
            validate_low_node_load,
        )

        with open(args.config) as f:
            raw = json.load(f)
        section = raw.get("lowNodeLoad", raw)
        la = decode_low_node_load(section)
        validate_low_node_load(la)
        pools = decode_low_node_load_pools(section)
    plugin = LowNodeLoadBalance(LowNodeLoad(snap, la), pools=pools)
    profile = Profile(
        name="koord-descheduler",
        balance_plugins=[plugin],
        dry_run=args.dry_run,
        max_evictions_per_round=args.max_evictions_per_round,
    )
    desched = Descheduler([profile], interval_s=max(args.interval, 1.0))

    def step(i: int):
        counts = desched.run_once(nodes, pods)
        return {"round": i, "profiles": counts}

    return _common.run_elected(
        args, "koord-descheduler", lambda stop: _common.loop_rounds(args, stop, step)
    )


if __name__ == "__main__":
    sys.exit(main())
