"""koord-runtime-proxy binary (reference ``cmd/koord-runtime-proxy/``):
CRI man-in-the-middle with hook dispatch. Without a kubelet/containerd
socket pair, the default ``--demo`` drives one pod sandbox + container
lifecycle through the proxy against an in-memory backend to prove the
hook chain and checkpoint store."""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from ..runtimeproxy.config import HookServerRegistration, parse_failure_policy
from ..runtimeproxy.dispatcher import Dispatcher
from ..runtimeproxy.proto import (
    ContainerMetadata,
    PodSandboxMetadata,
    RuntimeHookType,
)
from ..runtimeproxy.server import ContainerConfig, CRIProxy, PodSandboxConfig


class InMemoryRuntime:
    """Stand-in backend runtime (containerd) for the demo lifecycle."""

    def __init__(self) -> None:
        self.sandboxes: Dict[str, PodSandboxConfig] = {}
        self.containers: Dict[str, ContainerConfig] = {}
        self._n = 0

    def run_pod_sandbox(self, config: PodSandboxConfig) -> str:
        self._n += 1
        sid = f"sandbox-{self._n}"
        self.sandboxes[sid] = config
        return sid

    def stop_pod_sandbox(self, pod_id: str) -> None:
        self.sandboxes.pop(pod_id, None)

    def create_container(self, pod_id: str, config: ContainerConfig) -> str:
        self._n += 1
        cid = f"container-{self._n}"
        self.containers[cid] = config
        return cid

    def start_container(self, container_id: str) -> None:
        pass

    def stop_container(self, container_id: str) -> None:
        pass

    def update_container_resources(self, container_id: str, resources) -> None:
        pass


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-runtime-proxy")
    parser.add_argument(
        "--fail-policy", choices=["Fail", "Ignore"], default="Ignore"
    )
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    calls: List[str] = []
    dispatcher = Dispatcher()
    dispatcher.register(
        HookServerRegistration.create(
            name="audit",
            hook_types=frozenset(RuntimeHookType),
            handler=lambda hook, req: calls.append(hook.value),
            failure_policy=parse_failure_policy(args.fail_policy),
        )
    )

    backend = InMemoryRuntime()
    proxy = CRIProxy(backend, dispatcher=dispatcher)

    sid = proxy.run_pod_sandbox(
        PodSandboxConfig(
            metadata=PodSandboxMetadata(name="demo-pod", uid="demo-uid")
        )
    )
    cid = proxy.create_container(sid, ContainerConfig(metadata=ContainerMetadata(name="main")))
    proxy.start_container(cid)
    checkpointed = proxy.store.get_pod(sid) is not None
    proxy.stop_container(cid)
    proxy.stop_pod_sandbox(sid)

    print(json.dumps({"hooks_fired": calls, "sandbox_checkpointed": checkpointed}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
