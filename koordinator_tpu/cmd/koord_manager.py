"""koord-manager binary (reference ``cmd/koord-manager/main.go``):
slo-controller reconcilers (nodemetric + noderesource + nodeslo),
leader-elected like the controller-runtime manager."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..manager.nodemetric import NodeMetricController
from ..manager.noderesource import NodeResourceController
from ..manager.nodeslo import NodeSLOController
from ..utils.features import MANAGER_GATES
from . import _common


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-manager")
    _common.add_common_flags(parser)
    _common.add_sim_flags(parser)
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    _common.apply_feature_gates(MANAGER_GATES, args.feature_gates)

    snap, nodes, _pods, _hub = _common.build_snapshot(args)
    nodemetric = NodeMetricController()
    noderesource = NodeResourceController(snap)
    nodeslo = NodeSLOController()
    names = [n.meta.name for n in nodes]

    def step(i: int):
        specs = nodemetric.reconcile(names)
        batch = noderesource.reconcile()
        slos = {n: nodeslo.render(n).meta.name for n in names}
        return {
            "round": i,
            "nodemetric_specs": len(specs),
            "batch_resources": len(batch),
            "nodeslos": len(slos),
        }

    return _common.run_elected(
        args, "koord-manager", lambda stop: _common.loop_rounds(args, stop, step)
    )


if __name__ == "__main__":
    sys.exit(main())
