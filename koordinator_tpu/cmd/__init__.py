"""Deployable entry points — the analog of the reference's five binaries
under ``cmd/`` (koord-scheduler, koord-descheduler, koord-manager, koordlet,
koord-runtime-proxy; SURVEY §2.1).

Each module exposes ``main(argv) -> int`` and is runnable as
``python -m koordinator_tpu.cmd.<name>``. Control-plane daemons
(scheduler / manager / descheduler) gate their loops behind lease-based
leader election like the reference (``app/server.go:247-281``), via
``--leader-elect`` with a shared ``--lease-file`` lock.

Without an apiserver, cluster state comes from the built-in simulator
(``sim.cluster_gen``) or a JSON state file — the same substitution the
reference's kind-based e2e makes for a real cluster.
"""
