"""koordlet binary (reference ``cmd/koordlet/main.go``): the node agent
daemon — collectors, QoS strategies, runtime hooks, metric reporting."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..koordlet.daemon import Koordlet, KoordletConfig
from ..utils.features import KOORDLET_GATES
from . import _common


def build_parser() -> argparse.ArgumentParser:
    # koordlet is a per-node DaemonSet in the reference — no leader
    # election or reconcile rounds, so it takes only its own flags
    parser = argparse.ArgumentParser(prog="koordlet")
    parser.add_argument(
        "--feature-gates",
        default="",
        help="comma-separated key=bool overrides, e.g. Foo=true,Bar=false",
    )
    parser.add_argument("--node-name", default="node-local")
    parser.add_argument("--cgroup-root", default="/sys/fs/cgroup")
    parser.add_argument(
        "--duration",
        type=float,
        default=0.0,
        help="run for N seconds then exit (0 = forever)",
    )
    parser.add_argument("--collect-interval", type=float, default=1.0)
    parser.add_argument(
        "--kubelet-addr",
        default="",
        help="pull the pod list from this kubelet's /pods endpoint",
    )
    parser.add_argument("--kubelet-port", type=int, default=10255)
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    _common.apply_feature_gates(KOORDLET_GATES, args.feature_gates)

    cfg = KoordletConfig(
        node_name=args.node_name,
        cgroup_root=args.cgroup_root,
        collect_interval_s=args.collect_interval,
        kubelet_addr=args.kubelet_addr,
        kubelet_port=args.kubelet_port,
    )
    agent = Koordlet(cfg)
    agent.run(duration_s=args.duration or float("inf"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
