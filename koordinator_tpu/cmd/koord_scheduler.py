"""koord-scheduler binary (reference ``cmd/koord-scheduler/main.go``):
drains pending pods through the batched TPU solver, leader-elected."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from ..utils.features import SCHEDULER_GATES
from . import _common


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-scheduler")
    _common.add_common_flags(parser)
    _common.add_sim_flags(parser)
    parser.add_argument(
        "--batch-bucket", type=int, default=4096, help="solver batch shape"
    )
    parser.add_argument(
        "--config", default="", help="versioned plugin-args JSON (scheduler.config)"
    )
    parser.add_argument(
        "--mesh",
        type=int,
        default=0,
        metavar="N",
        help=(
            "multi-chip mode: shard the solver over an N-device (dp, tp) "
            "mesh (parallel.sharded; pod rows on dp, node table on tp, "
            "collectives over ICI). 0 = single-device. The reference "
            "analog is the parallelism wired into the scheduler at "
            "cmd/koord-scheduler/app/server.go:417"
        ),
    )
    parser.add_argument(
        "--latency",
        type=float,
        default=0.0,
        metavar="PODS_PER_SEC",
        help=(
            "latency operating point: feed pending pods through the "
            "StreamScheduler (adaptive batches + node sampling) at this "
            "arrival rate instead of draining in throughput chunks; "
            "per-pod enqueue→bind p50/p99 is reported per round. The "
            "reference's latency discipline is the per-pod loop under "
            "the SchedulerMonitor watchdog "
            "(frameworkext/scheduler_monitor.go:43-47)"
        ),
    )
    parser.add_argument(
        "--latency-max-batch",
        type=int,
        default=128,
        help="StreamScheduler adaptive batch cap in --latency mode",
    )
    parser.add_argument(
        "--serve",
        default="",
        metavar="ADDR",
        help=(
            "long-lived solver-sidecar mode: serve the gRPC snapshot/"
            "nominate channel on ADDR (e.g. 127.0.0.1:50051) instead of "
            "the sim loop — the north-star deployment shape (control "
            "plane ships deltas, solver answers nominations)"
        ),
    )
    parser.add_argument(
        "--journal-file",
        default="",
        metavar="PATH",
        help=(
            "durable write-ahead bind journal (HA failover): append one "
            "JSONL record per commit intent/bind/forget to PATH so a "
            "restart rebuilds acknowledged placements via journal replay "
            "(runtime.recovery) instead of a cold resync; pairs with "
            "--leader-elect + --lease-file for leader-elected "
            "multi-process failover (epoch-FENCED commits additionally "
            "need the library-level EpochFence/LeaderCoordinator wiring)"
        ),
    )
    parser.add_argument(
        "--flight-file",
        default="",
        metavar="PATH",
        help=(
            "crash-surviving flight recorder (distributed-observability "
            "follow-on): append one JSONL per-cycle summary record "
            "(stage_ms, gate verdicts, speculation outcome, queue depth) "
            "to PATH beside --journal-file, so a restarted process "
            "adopts the dead incarnation's last-N cycles and serves them "
            "at /debug/flightrecorder — the post-mortem black box"
        ),
    )
    return parser


def main(
    argv: Optional[List[str]] = None,
    _stop_event=None,
    _on_serve=None,
) -> int:
    """``_stop_event``/``_on_serve`` are embedding hooks for --serve mode:
    a threading.Event that ends the serve loop, and a callback receiving
    (server, port) once listening — tests and embedders use them instead
    of signals/stdout scraping."""
    args = build_parser().parse_args(argv)
    _common.apply_feature_gates(SCHEDULER_GATES, args.feature_gates)

    la_args = LoadAwareArgs()
    numa_scoring = device_scoring = None
    shortlist_k = 64
    if args.config:
        import json

        from ..scheduler.config import (
            decode_device_share,
            decode_load_aware,
            decode_node_numa,
            decode_solver_tuning,
            validate_device_share,
            validate_load_aware,
            validate_solver_tuning,
        )

        with open(args.config) as f:
            raw = json.load(f)
        la_args = decode_load_aware(raw.get("loadAware", raw))
        validate_load_aware(la_args)
        if "deviceShare" in raw:
            ds = decode_device_share(raw["deviceShare"])
            validate_device_share(ds)
            device_scoring = ds.scoring_strategy
        if "nodeNUMAResource" in raw:
            numa_scoring = decode_node_numa(
                raw["nodeNUMAResource"]
            ).scoring_strategy
        if "solverTuning" in raw:
            st = decode_solver_tuning(raw["solverTuning"])
            validate_solver_tuning(st)
            shortlist_k = st.shortlist_k

    if args.serve:
        import signal
        import threading

        from ..runtime.snapshot_channel import SolverService, serve

        if numa_scoring is not None or device_scoring is not None:
            print(
                "koord-scheduler: deviceShare/nodeNUMAResource scoring "
                "strategies are not yet applied in --serve mode (the "
                "snapshot channel carries no device/topology inventory) — "
                "config accepted but inert",
                file=sys.stderr,
            )
        mesh = None
        if args.mesh > 0:
            from ..parallel.sharded import make_mesh

            mesh = make_mesh(args.mesh)
            print(
                f"koord-scheduler: solver sharded over mesh "
                f"{dict(mesh.shape)}",
                file=sys.stderr,
            )
        service = SolverService(
            args=la_args, batch_bucket=args.batch_bucket, mesh=mesh
        )
        server, port = serve(service, address=args.serve)
        print(f"koord-scheduler: solver service listening on port {port}", flush=True)
        stop = _stop_event if _stop_event is not None else threading.Event()
        try:
            for sig in (signal.SIGINT, signal.SIGTERM):
                signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            # non-main thread: the embedder must supply _stop_event —
            # without one there would be no way to ever return
            if _stop_event is None:
                raise RuntimeError(
                    "--serve from a non-main thread requires a stop event "
                    "(main(..., _stop_event=...))"
                )
        if _on_serve is not None:
            _on_serve(server, port)
        stop.wait()
        server.stop(grace=5.0)
        return 0

    snap, _nodes, pods, hub = _common.build_snapshot(args)
    mesh = None
    if args.mesh > 0:
        from ..parallel.sharded import make_mesh

        mesh = make_mesh(args.mesh)
        print(
            f"koord-scheduler: solver sharded over mesh {dict(mesh.shape)}",
            file=sys.stderr,
        )
    numa = devices = None
    if numa_scoring is not None:
        import sys as _sys

        from ..scheduler.plugins.nodenumaresource import NUMAManager

        numa = NUMAManager(snap, scoring_strategy=numa_scoring)
        print(
            "koord-scheduler: nodeNUMAResource scoring configured but the "
            "sim feed registers no CPU topology — strategy is inert until "
            "topologies are registered",
            file=_sys.stderr,
        )
    if device_scoring is not None:
        from ..api.types import Device, DeviceInfo, ObjectMeta
        from ..scheduler.plugins.deviceshare import DeviceManager

        devices = DeviceManager(snap, scoring_strategy=device_scoring)
        if args.sim_gpus > 0:
            for node in _nodes:
                devices.upsert_device(
                    Device(
                        meta=ObjectMeta(name=node.meta.name),
                        devices=[
                            DeviceInfo(dev_type="gpu", minor=g)
                            for g in range(args.sim_gpus)
                        ],
                    )
                )
        else:
            import sys as _sys

            print(
                "koord-scheduler: deviceShare scoring configured with no "
                "device inventory — pass --sim-gpus N to give sim nodes "
                "GPUs, or feed Device objects",
                file=_sys.stderr,
            )
    journal = None
    if args.journal_file:
        from ..core.journal import BindJournal, FileJournalStore

        journal = BindJournal(FileJournalStore(args.journal_file))
    latency_mode = args.latency > 0
    sched = BatchScheduler(
        snap,
        la_args,
        batch_bucket=(
            args.latency_max_batch if latency_mode else args.batch_bucket
        ),
        # latency mode runs the kube-scheduler adaptive node sampling
        # (PercentageOfNodesToScore=0 → the upstream default curve) so a
        # cycle over a 10k-node table is a sampled-window solve
        percentage_of_nodes_to_score=0 if latency_mode else 100,
        numa=numa,
        devices=devices,
        mesh=mesh,
        journal=journal,
        shortlist_k=shortlist_k,
    )
    if args.flight_file:
        import uuid

        from ..core.journal import FileJournalStore
        from ..obs.flightrecorder import FlightRecorder

        recorder = FlightRecorder(
            FileJournalStore(args.flight_file),
            incarnation=f"koord-scheduler-{uuid.uuid4().hex[:8]}",
        )
        adopted = recorder.recovered_records()
        if adopted:
            print(
                f"koord-scheduler: flight recorder adopted "
                f"{len(adopted)} record(s) from previous incarnation(s)",
                file=sys.stderr,
            )
        sched.attach_flight_recorder(recorder)
    # the rest of the scheduler's world view (pods/devices/quotas/gangs)
    # flows through the same informer hub that already feeds the snapshot
    hub.wire_scheduler(sched, include_snapshot=False)
    hub.start()
    if journal is not None:
        # restart recovery: replay acknowledged bindings the informer
        # feed doesn't carry (assumed-but-unbound) before scheduling
        from ..runtime.recovery import recover_scheduler

        rep = recover_scheduler(sched, journal, hub=hub, verify=False)
        if rep.replayed or rep.reconfirmed:
            print(
                f"koord-scheduler: journal recovery replayed="
                f"{rep.replayed} reconfirmed={rep.reconfirmed} "
                f"skipped={rep.skipped_missing_node}",
                file=sys.stderr,
            )
    pending = [p for p in pods if not p.spec.node_name]

    if latency_mode:
        import time as _time

        from ..scheduler.stream import StreamScheduler

        stream = StreamScheduler(sched, max_batch=args.latency_max_batch)
        arrivals = list(pending)
        state = {"i": 0, "t0": _time.perf_counter(), "next": 0.0}

        def step(i: int):
            # feed arrivals at --latency pods/s (deterministic spacing —
            # the sim is a feed, not a benchmark), pump one cycle, and
            # report per-pod enqueue→bind latency percentiles
            import numpy as _np

            now = _time.perf_counter() - state["t0"]
            while state["next"] <= now and state["i"] < len(arrivals):
                stream.submit(
                    arrivals[state["i"]], now=state["t0"] + state["next"]
                )
                state["i"] += 1
                state["next"] += 1.0 / args.latency
            res = stream.pump()
            lat_ms = [l * 1e3 for _p, node, l in res if node is not None]
            summary = {
                "round": i,
                "mode": "latency",
                "rate_pods_per_sec": args.latency,
                "decided": len(res),
                "bound": len(lat_ms),
                "backlog": stream.backlog(),
                "pod_p50_ms": (
                    round(float(_np.percentile(lat_ms, 50)), 2)
                    if lat_ms
                    else None
                ),
                "pod_p99_ms": (
                    round(float(_np.percentile(lat_ms, 99)), 2)
                    if lat_ms
                    else None
                ),
            }
            return summary

    else:

        def step(i: int):
            nonlocal pending
            out = sched.schedule(pending)
            summary = {
                "round": i,
                "bound": len(out.bound),
                "unschedulable": len(out.unschedulable),
                "solver_rounds": out.rounds_used,
            }
            pending = list(out.unschedulable)
            return summary

    return _common.run_elected(
        args, "koord-scheduler", lambda stop: _common.loop_rounds(args, stop, step)
    )


if __name__ == "__main__":
    sys.exit(main())
