"""Shared daemon plumbing: flags, feature gates, leader-election gating.

Mirrors the option surface every reference binary shares (cobra+pflag
componentconfig: ``--feature-gates``, ``--leader-elect``, pprof/metrics
addresses) in argparse form.
"""

from __future__ import annotations

import argparse
import json
import signal
import threading
from typing import Callable, Optional

from ..core.snapshot import ClusterSnapshot
from ..sim.cluster_gen import GenConfig, gen_nodes, gen_pods
from ..utils.features import FeatureGate
from ..utils.leaderelection import FileLeaseLock, InMemoryLeaseLock, LeaderElector


def add_common_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--feature-gates",
        default="",
        help="comma-separated key=bool overrides, e.g. Foo=true,Bar=false",
    )
    parser.add_argument(
        "--leader-elect",
        action="store_true",
        help="gate the loop behind lease-based leader election",
    )
    parser.add_argument(
        "--lease-file",
        default="",
        help="lease lock path (cross-process); in-memory when empty",
    )
    parser.add_argument("--identity", default="", help="leader election identity")
    parser.add_argument(
        "--rounds", type=int, default=1, help="loop iterations (0 = forever)"
    )
    parser.add_argument(
        "--interval", type=float, default=0.0, help="seconds between rounds"
    )


def add_sim_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--sim-nodes", type=int, default=100)
    parser.add_argument("--sim-pods", type=int, default=500)
    parser.add_argument(
        "--sim-gpus",
        type=int,
        default=0,
        help="GPUs per simulated node (used when deviceShare is configured)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--state-file",
        default="",
        help="JSON cluster state (overrides the simulator)",
    )


def apply_feature_gates(gates: FeatureGate, raw: str) -> None:
    if not raw:
        return
    overrides = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        overrides[key.strip()] = val.strip().lower() in ("true", "1", "yes")
    gates.set_from_map(overrides)


def load_world(args: argparse.Namespace):
    """(nodes, metrics, pods) from --state-file or the simulator — pure
    data, no consumer state touched."""
    if args.state_file:
        with open(args.state_file) as f:
            state = json.load(f)
        from ..api.types import (
            Node,
            NodeMetric,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
            ResourceMetric,
        )

        nodes = [
            Node(
                meta=ObjectMeta(name=n["name"], labels=n.get("labels", {})),
                status=NodeStatus(allocatable=n.get("allocatable", {})),
            )
            for n in state.get("nodes", [])
        ]
        metrics = [
            NodeMetric(
                meta=ObjectMeta(name=m["name"]),
                node_usage=ResourceMetric(usage=m.get("usage", {})),
                update_time=m.get("update_time", 0.0),
            )
            for m in state.get("node_metrics", [])
        ]
        pods = [
            Pod(
                meta=ObjectMeta(
                    name=p["name"],
                    namespace=p.get("namespace", "default"),
                    labels=p.get("labels", {}),
                ),
                spec=PodSpec(
                    requests=p.get("requests", {}),
                    priority=p.get("priority"),
                    node_name=p.get("node_name", ""),
                ),
            )
            for p in state.get("pods", [])
        ]
        return nodes, metrics, pods
    cfg = GenConfig(n_nodes=args.sim_nodes, n_pods=args.sim_pods, seed=args.seed)
    nodes, metrics = gen_nodes(cfg)
    return nodes, metrics, gen_pods(cfg)


def build_snapshot(args: argparse.Namespace):
    """(snapshot, nodes, pods, hub): the snapshot is populated THROUGH
    the informer layer — a ClusterStateHub's Node/NodeMetric informers
    apply the world, exactly how the reference binaries consume
    ``pkg/client`` shared informers (the round-2 review found the
    informer layer tested but driving nothing). The returned hub stays
    live: further publishes/deletes keep flowing into the snapshot, and
    a severed watch self-heals by re-list."""
    from ..runtime.statehub import ClusterStateHub

    snap = ClusterSnapshot()
    hub = ClusterStateHub()
    hub.wire_snapshot(snap)
    hub.start()
    nodes, metrics, pods = load_world(args)
    for n in nodes:
        hub.publish(hub.nodes, n)
    for m in metrics:
        hub.publish(hub.node_metrics, m)
    hub.wait_synced()
    return snap, nodes, pods, hub


#: in-process lease locks, one per component — distinct daemons embedded in
#: one process each get their own leadership, like their separate Lease
#: objects in the reference
_SHARED_LOCKS: dict = {}


def run_elected(
    args: argparse.Namespace,
    component: str,
    body: Callable[[threading.Event], int],
) -> int:
    """Run ``body(stop)`` — behind leader election when --leader-elect.

    The body gets a stop event wired to SIGTERM/SIGINT; with election on,
    losing the lease also sets it (the reference exits outright —
    ``app/server.go`` leaderelection.RunOrDie OnStoppedLeading → klog.Fatal;
    a library can't exit the interpreter, so stopping the loop is the
    equivalent).
    """
    stop = threading.Event()
    prev_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev_handlers[sig] = signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # not the main thread (tests)

    try:
        if not args.leader_elect:
            return body(stop)

        if args.lease_file:
            lock = FileLeaseLock(args.lease_file)
        else:
            lock = _SHARED_LOCKS.setdefault(component, InMemoryLeaseLock())
        import os

        identity = args.identity or f"{component}-{os.getpid()}"
        elector = LeaderElector(lock, identity)

        if not elector.acquire(stop):
            return 0

        elector.on_stopped_leading = stop.set
        renewer = threading.Thread(
            target=elector.renew_loop, args=(stop,), daemon=True
        )
        renewer.start()
        try:
            return body(stop)
        finally:
            stop.set()
            renewer.join(timeout=5.0)
            elector.release()
    finally:
        for sig, handler in prev_handlers.items():
            signal.signal(sig, handler)


def loop_rounds(
    args: argparse.Namespace,
    stop: threading.Event,
    step: Callable[[int], Optional[dict]],
) -> int:
    """Run ``step(i)`` every --interval for --rounds (0 = until stopped),
    printing each round's summary as a JSON line."""
    # forever mode must pace itself even with the default --interval 0,
    # or the loop busy-spins a core and floods stdout
    interval = args.interval if args.interval > 0 else (1.0 if not args.rounds else 0.0)
    i = 0
    while not stop.is_set():
        out = step(i)
        if out is not None:
            print(json.dumps(out), flush=True)
        i += 1
        if args.rounds and i >= args.rounds:
            break
        if interval > 0 and stop.wait(interval):
            break
    return 0
