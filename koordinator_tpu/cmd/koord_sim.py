"""koord-sim binary: the full §3.3 feedback loop as ONE long-lived process.

The reference exercises its cross-component data flow on a kind cluster
(SURVEY §4: koordlet → NodeMetric → slo-controller → scheduler →
runtimehooks); this binary is the rebuild's stand-in: it composes every
component in-process and drives them for N simulated minutes with
per-tick consistency invariants (driver:
``koordinator_tpu/sim/longrun.py``; asserted invariants:
``tests/test_longrun_loop.py``).

    python -m koordinator_tpu.cmd.koord_sim --minutes 30 --nodes 8
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-sim")
    parser.add_argument("--minutes", type=float, default=10.0)
    parser.add_argument("--nodes", type=int, default=6)
    parser.add_argument("--tick-s", type=float, default=15.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--quiet", action="store_true", help="suppress per-report narration"
    )
    parser.add_argument(
        "--chaos-ticks",
        default="",
        help="comma-separated tick numbers at which every informer watch "
        "is severed (apiserver-restart chaos); the loop must re-converge",
    )
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    from ..sim.longrun import run_loop

    stats = run_loop(
        minutes=args.minutes,
        tick_s=args.tick_s,
        n_nodes=args.nodes,
        seed=args.seed,
        verbose=not args.quiet,
        chaos_ticks=tuple(
            int(x) for x in args.chaos_ticks.split(",") if x.strip()
        ),
    )
    print(json.dumps(stats))
    return 0 if stats["bound"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
