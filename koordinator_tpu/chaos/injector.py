"""Named fault points with deterministic per-point schedules.

Mechanism
---------

Components call ``injector.fire("domain.point")`` at each fault point.
With nothing armed the call is one attribute read and a ``return False``
— cheap enough to leave wired in hot paths permanently (the
``tests/test_chaos.py`` overhead guard enforces this, mirroring the
``test_obs_overhead`` zero-allocation contract for disabled tracing).

Arming a point attaches a :class:`FaultSpec` schedule:

* ``probability`` — chance the point fires per evaluation (seeded RNG);
* ``at_hits``     — fire exactly on these 1-based evaluations instead
  (the deterministic form: "crash on the 3rd commit");
* ``latency_s``   — injected delay when the point fires;
* ``error``       — exception (instance, class or zero-arg factory)
  raised when the point fires;
* ``times``       — cap on total fires (``times=1`` = fire once).

Determinism: one ``random.Random(seed)`` drives every probabilistic
decision in arm order, and each fired fault appends ``(seq, point,
kind)`` to :attr:`FaultInjector.trace` — so an identical call sequence
under the same seed yields an identical fault trace (the chaos soak
asserts this property end to end).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


class ChaosError(RuntimeError):
    """Default exception type raised by error-mode fault points."""


@dataclass
class FaultSpec:
    """Schedule for one named fault point."""

    point: str
    probability: float = 1.0
    latency_s: float = 0.0
    error: Optional[object] = None      # exception | class | () -> exception
    times: Optional[int] = None         # remaining fires; None = unlimited
    at_hits: Optional[frozenset] = None  # fire exactly on these evaluations
    hits: int = 0                       # evaluations seen
    fired: int = 0                      # faults actually injected


def _make_error(error: object, point: str) -> BaseException:
    if isinstance(error, BaseException):
        return error
    if isinstance(error, type) and issubclass(error, BaseException):
        return error(f"injected fault at {point}")
    if callable(error):
        return error()
    return ChaosError(f"injected fault at {point}: {error!r}")


class FaultInjector:
    """Seedable fault-point evaluator with a reproducible trace.

    ``fire(point)`` returns True when the fault fired and the *caller*
    implements its effect (drop the RPC, corrupt the row); latency and
    error effects are applied by the injector itself. ``sleep`` is
    injectable so tests can fake injected latency.
    """

    def __init__(
        self,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        counter=None,
    ):
        self.seed = seed
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._specs: Dict[str, FaultSpec] = {}
        self._lock = threading.Lock()
        #: fast-path guard: False ⇒ fire() is one attribute read + return
        self.enabled = False
        #: (seq, point, kind) per injected fault — the fault trace
        self.trace: List[Tuple[int, str, str]] = []
        self._seq = 0
        #: optional ``fault_injected_total{point}`` Counter
        self.counter = counter

    # ---- arming ----

    def arm(
        self,
        point: str,
        probability: float = 1.0,
        latency_s: float = 0.0,
        error: Optional[object] = None,
        times: Optional[int] = None,
        at_hits: Optional[object] = None,
    ) -> FaultSpec:
        spec = FaultSpec(
            point=point,
            probability=probability,
            latency_s=latency_s,
            error=error,
            times=times,
            at_hits=frozenset(at_hits) if at_hits is not None else None,
        )
        with self._lock:
            self._specs[point] = spec
            self.enabled = True
        return spec

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._specs.clear()
            else:
                self._specs.pop(point, None)
            self.enabled = bool(self._specs)

    def spec(self, point: str) -> Optional[FaultSpec]:
        return self._specs.get(point)

    def bind_counter(self, counter) -> None:
        """Attach a ``fault_injected_total{point}`` Counter."""
        self.counter = counter

    # ---- evaluation ----

    def fire(self, point: str) -> bool:
        """Evaluate ``point`` against its armed schedule.

        Sleeps ``latency_s`` / raises ``error`` per the spec; returns
        True when the fault fired and the caller owns the effect.
        """
        if not self.enabled:
            return False
        return self._fire(point)

    def _fire(self, point: str) -> bool:
        with self._lock:
            spec = self._specs.get(point)
            if spec is None:
                return False
            spec.hits += 1
            if spec.times is not None and spec.fired >= spec.times:
                return False
            if spec.at_hits is not None:
                hit = spec.hits in spec.at_hits
            elif spec.probability >= 1.0:
                hit = True
            else:
                hit = self._rng.random() < spec.probability
            if not hit:
                return False
            spec.fired += 1
            self._seq += 1
            kind = (
                "error"
                if spec.error is not None
                else ("latency" if spec.latency_s > 0 else "fault")
            )
            self.trace.append((self._seq, point, kind))
            latency = spec.latency_s
            error = spec.error
        if self.counter is not None:
            self.counter.labels(point=point).inc()
        if latency > 0:
            self._sleep(latency)
        if error is not None:
            raise _make_error(error, point)
        return True

    # ---- introspection ----

    def fired_counts(self) -> Dict[str, int]:
        with self._lock:
            return {p: s.fired for p, s in self._specs.items()}


#: shared always-disabled injector for components with no chaos wired —
#: the default value of every ``chaos=`` parameter in the package
NULL_INJECTOR = FaultInjector()
