"""Deterministic fault injection for the solve pipeline.

A seedable, zero-overhead-when-disabled fault layer threaded through every
failure domain of the rebuild — the gRPC snapshot channel, the statehub
informers, solver dispatch, the commit path and the koordlet ticks — as
*injectable hooks*, never monkeypatches: each component takes an optional
:class:`FaultInjector` and defaults to the shared :data:`NULL_INJECTOR`,
whose ``fire()`` is a single attribute read (the same discipline as
``obs.trace``'s disabled-mode span singleton).

Gavel (arXiv:2008.09213) and Synergy (arXiv:2110.06073) both observe that
a cluster scheduler's value evaporates if a round can wedge or corrupt
shared state; this module exists to *prove* the recovery paths — the
generation-gap resync, the informer re-list, the solver fallback ladder,
the transactional Reserve journal — under a reproducible fault trace
(same seed ⇒ same trace).

See :mod:`injector` for the mechanism and ``sim.longrun.run_chaos_soak``
for the full composition.
"""

from .injector import (
    NULL_INJECTOR,
    ChaosError,
    FaultInjector,
    FaultSpec,
)

__all__ = [
    "NULL_INJECTOR",
    "ChaosError",
    "FaultInjector",
    "FaultSpec",
]
