"""Pod validating webhook checks.

Rebuild of ``pkg/webhook/pod/validating/`` (``verify_annotations.go``,
QoS/priority consistency): reject pods whose QoS class, priority band and
resource spec disagree with the annotation protocol before they reach the
scheduler.
"""

from __future__ import annotations

from typing import List

from ..api import extension as ext
from ..api.extension import PriorityClass, QoSClass
from ..api.types import Pod


def validate_pod(pod: Pod) -> List[str]:
    """Returns a list of violation messages (empty = valid).

    Rules (reference ``pod/validating``):
      * BE pods must not request exclusive cpus (integer cpu + LSR/LSE only)
      * LSE/LSR requires prod priority band
      * BE pods should request batch-tier resources, not raw cpu/memory
        limits beyond requests
      * priority value must lie in the band implied by any explicit
        koord priority class label
    """
    errors: List[str] = []
    qos = pod.qos
    band = pod.priority_class

    if qos in (QoSClass.LSE, QoSClass.LSR):
        if band is not PriorityClass.PROD:
            errors.append(
                f"{qos.name} pods require prod priority (9000-9999), got "
                f"{pod.spec.priority}"
            )
    if qos is QoSClass.BE:
        if band in (PriorityClass.PROD, PriorityClass.MID):
            errors.append(
                f"BE pods must use batch/free priority bands, got {pod.spec.priority}"
            )
        cpu = pod.spec.requests.get(ext.RES_CPU, 0.0)
        limit_cpu = pod.spec.limits.get(ext.RES_CPU)
        if limit_cpu is not None and cpu > 0 and limit_cpu < cpu:
            errors.append("cpu limit below request")
    explicit = pod.meta.labels.get(ext.LABEL_POD_PRIORITY)
    if explicit is not None:
        try:
            explicit_band = PriorityClass[explicit.upper()]
        except KeyError:
            errors.append(f"unknown priority class label {explicit!r}")
        else:
            if (
                pod.spec.priority is not None
                and PriorityClass.from_priority(pod.spec.priority)
                is not explicit_band
            ):
                errors.append(
                    f"priority {pod.spec.priority} outside the "
                    f"{explicit_band.name} band"
                )
    gpu_whole, gpu_share = ext.parse_gpu_request(pod.spec.requests)
    if gpu_whole > 0 and gpu_share > 0:
        errors.append("multi-GPU pods cannot also request a fractional share")
    return errors
