"""Pod validating webhook checks.

Rebuild of ``pkg/webhook/pod/validating/`` — QoS/priority consistency,
forbidden annotations (``verify_annotations.go:60-76``), device-resource
declaration rules (``verify_device_resource.go:68-176``), and
annotation-payload shape verification for the scheduling protocol
annotations: reject pods whose QoS class, priority band, resource spec or
annotations disagree with the protocol before they reach the scheduler.
"""

from __future__ import annotations

import json
from typing import List

from ..api import extension as ext
from ..api.extension import PriorityClass, QoSClass
from ..api.types import Pod

#: annotations only the scheduler itself may write (the reference forbids
#: the reserve-pod marker the same way, ``verify_annotations.go:60-63``)
FORBIDDEN_ANNOTATIONS = (
    f"scheduling.{ext.DOMAIN}/reserve-pod",
)


def validate_pod(pod: Pod) -> List[str]:
    """Returns a list of violation messages (empty = valid)."""
    errors: List[str] = []
    errors += _validate_qos_priority(pod)
    errors += _validate_forbidden_annotations(pod)
    errors += _validate_device_resources(pod)
    errors += _validate_annotation_shapes(pod)
    return errors


def _validate_qos_priority(pod: Pod) -> List[str]:
    """QoS/priority band consistency (the round-1 core rules)."""
    errors: List[str] = []
    qos = pod.qos
    band = pod.priority_class

    if qos in (QoSClass.LSE, QoSClass.LSR):
        if band is not PriorityClass.PROD:
            errors.append(
                f"{qos.name} pods require prod priority (9000-9999), got "
                f"{pod.spec.priority}"
            )
    if qos is QoSClass.BE:
        if band in (PriorityClass.PROD, PriorityClass.MID):
            errors.append(
                f"BE pods must use batch/free priority bands, got {pod.spec.priority}"
            )
        cpu = pod.spec.requests.get(ext.RES_CPU, 0.0)
        limit_cpu = pod.spec.limits.get(ext.RES_CPU)
        if limit_cpu is not None and cpu > 0 and limit_cpu < cpu:
            errors.append("cpu limit below request")
    explicit = pod.meta.labels.get(ext.LABEL_POD_PRIORITY_CLASS)
    if explicit is not None:
        try:
            explicit_band = PriorityClass[explicit.upper()]
        except KeyError:
            errors.append(f"unknown priority class label {explicit!r}")
        else:
            if (
                pod.spec.priority is not None
                and PriorityClass.from_priority(pod.spec.priority)
                is not explicit_band
            ):
                errors.append(
                    f"priority {pod.spec.priority} outside the "
                    f"{explicit_band.name} band"
                )
    # the koordinator.sh/priority label is the NUMERIC sub-priority
    # (reference GetPodSubPriority, priority.go:103-113)
    sub = pod.meta.labels.get(ext.LABEL_POD_PRIORITY)
    if sub is not None:
        try:
            int(sub)
        except ValueError:
            errors.append(f"priority label must be an integer, got {sub!r}")
    return errors


def _validate_forbidden_annotations(pod: Pod) -> List[str]:
    """Scheduler-owned annotations may not be set at admission."""
    return [
        f"annotation {key} cannot be set on pod create/update"
        for key in FORBIDDEN_ANNOTATIONS
        if key in pod.meta.annotations
    ]


def _validate_device_resources(pod: Pod) -> List[str]:
    """Device declaration rules (``verify_device_resource.go:68-176``):
    the koord percentage-GPU API and the shared-GPU API are mutually
    exclusive; percentage GPU must be >0 and, above 100, a multiple of
    100; shared GPU needs exactly one of gpu-memory / gpu-memory-ratio,
    with core/ratio multiples of the share count."""
    errors: List[str] = []
    req = pod.spec.requests
    koord_gpu = req.get(ext.RES_KOORD_GPU)
    gpu_shared = req.get(ext.RES_GPU_SHARED)

    if koord_gpu is not None and gpu_shared is not None:
        return ["cannot declare GPU and GPU share at the same time"]

    if koord_gpu is not None:
        if koord_gpu <= 0:
            errors.append("the requested GPU must be greater than zero")
        elif koord_gpu > 100 and koord_gpu % 100 != 0:
            errors.append("the requested GPU must be a percentage of 100")

    if gpu_shared is not None:
        if gpu_shared <= 0:
            errors.append("the requested GPU share must be greater than zero")
        mem = req.get(ext.RES_GPU_MEMORY, 0.0)
        ratio = req.get(ext.RES_GPU_MEMORY_RATIO, 0.0)
        core = req.get(ext.RES_GPU_CORE, 0.0)
        if mem == 0 and ratio == 0:
            errors.append("GPU memory and GPU memory ratio are both zero")
        if mem != 0 and ratio != 0:
            errors.append(
                "cannot declare GPU memory and GPU memory ratio at the same time"
            )
        if gpu_shared > 0:
            if core and core % gpu_shared != 0:
                errors.append("the requested gpu-core must be a multiple of shared")
            if ratio and ratio % gpu_shared != 0:
                errors.append(
                    "the requested gpu-memory-ratio must be a multiple of shared"
                )

    whole, share = ext.parse_gpu_request(req)
    if whole > 0 and share > 0:
        errors.append("multi-GPU pods cannot also request a fractional share")
    rdma = req.get(ext.RES_RDMA)
    if rdma is not None and rdma <= 0:
        errors.append("the requested RDMA must be greater than zero")
    fpga = req.get(ext.RES_FPGA)
    if fpga is not None and fpga <= 0:
        errors.append("the requested FPGA must be greater than zero")
    return errors


def _validate_annotation_shapes(pod: Pod) -> List[str]:
    """Scheduling-protocol annotations must carry well-formed payloads —
    a malformed shape silently degrades scheduling behavior otherwise
    (resource-spec → Default bind policy, partition-spec → no bandwidth
    demand, …), so admission rejects it loudly."""
    errors: List[str] = []
    ann = pod.meta.annotations

    def parsed(key):
        raw = ann.get(key)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except (ValueError, TypeError):
            errors.append(f"annotation {key} is not valid JSON")
            return None

    spec = parsed(ext.ANNOTATION_RESOURCE_SPEC)
    if spec is not None and not isinstance(spec, dict):
        errors.append(f"annotation {ext.ANNOTATION_RESOURCE_SPEC} must be an object")
    elif isinstance(spec, dict):
        policy = spec.get("preferredCPUBindPolicy")
        if policy is not None and policy not in (
            "Default",
            "FullPCPUs",
            "SpreadByPCPUs",
            "ConstrainedBurst",
        ):
            errors.append(f"unknown preferredCPUBindPolicy {policy!r}")

    status = parsed(ext.ANNOTATION_RESOURCE_STATUS)
    if status is not None:
        # resource-status is scheduler-written; on user objects it must at
        # least be the right shape (object with optional cpuset string /
        # numaNodeResources list)
        if not isinstance(status, dict):
            errors.append(
                f"annotation {ext.ANNOTATION_RESOURCE_STATUS} must be an object"
            )
        else:
            if "cpuset" in status and not isinstance(status["cpuset"], str):
                errors.append("resource-status cpuset must be a string")
            nnr = status.get("numaNodeResources")
            if nnr is not None and (
                not isinstance(nnr, list)
                or not all(isinstance(z, dict) and "node" in z for z in nnr)
            ):
                errors.append(
                    "resource-status numaNodeResources must be a list of "
                    "{node: ...} objects"
                )

    alloc = parsed(ext.ANNOTATION_DEVICE_ALLOCATED)
    if alloc is not None:
        if not isinstance(alloc, dict):
            errors.append(
                f"annotation {ext.ANNOTATION_DEVICE_ALLOCATED} must be an object"
            )
        else:
            for dev_type, entries in alloc.items():
                if not isinstance(entries, list) or not all(
                    isinstance(e, dict) and isinstance(e.get("minor"), int)
                    for e in entries
                ):
                    errors.append(
                        f"device-allocated[{dev_type}] must be a list of "
                        "{minor, resources} objects"
                    )

    affinity = parsed(ext.ANNOTATION_RESERVATION_AFFINITY)
    if affinity is not None and not isinstance(affinity, dict):
        errors.append(
            f"annotation {ext.ANNOTATION_RESERVATION_AFFINITY} must be an object"
        )

    part = parsed(ext.ANNOTATION_GPU_PARTITION_SPEC)
    if part is not None:
        if not isinstance(part, dict):
            errors.append(
                f"annotation {ext.ANNOTATION_GPU_PARTITION_SPEC} must be an object"
            )
        else:
            bw = part.get("ringBusBandwidth")
            if bw is not None and not isinstance(bw, (int, float)):
                errors.append("gpu-partition-spec ringBusBandwidth must be numeric")
            pol = part.get("allocatePolicy")
            if pol is not None and pol not in ("Restricted", "BestEffort"):
                errors.append(f"unknown gpu-partition allocatePolicy {pol!r}")

    if ext.ANNOTATION_DEVICE_JOINT_ALLOCATE in ann:
        if ext.parse_device_joint_allocate(ann) is None:
            errors.append(
                f"annotation {ext.ANNOTATION_DEVICE_JOINT_ALLOCATE} must carry "
                "deviceTypes: [string, ...]"
            )
    return errors
