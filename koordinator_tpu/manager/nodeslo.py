"""NodeSLO controller: render per-node QoS strategy from cluster config.

Rebuild of ``pkg/slo-controller/nodeslo/``: the dynamic-config channel —
a cluster-level strategy (the reference's ``slo-controller-config``
ConfigMap, ``apis/configuration/slo_controller_config.go``) merged with
per-node overrides, rendered into one NodeSLO object per node that the
node agent enforces (qosmanager/runtimehooks).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional

from ..api.types import (
    CPUBurstStrategy,
    NodeSLO,
    ObjectMeta,
    ResourceThresholdStrategy,
)


@dataclasses.dataclass
class SLOControllerConfig:
    """Cluster default strategies + per-node-label overrides."""

    threshold: ResourceThresholdStrategy = dataclasses.field(
        default_factory=lambda: ResourceThresholdStrategy(enable=True)
    )
    cpu_burst: CPUBurstStrategy = dataclasses.field(default_factory=CPUBurstStrategy)
    #: node-label-selector -> override strategies (first match wins)
    node_overrides: Dict[str, ResourceThresholdStrategy] = dataclasses.field(
        default_factory=dict
    )


class NodeSLOController:
    def __init__(self, config: Optional[SLOControllerConfig] = None):
        self.config = config or SLOControllerConfig()
        self._rendered: Dict[str, NodeSLO] = {}

    def render(
        self, node_name: str, node_labels: Optional[Mapping[str, str]] = None
    ) -> NodeSLO:
        threshold = self.config.threshold
        for selector, override in self.config.node_overrides.items():
            key, _, value = selector.partition("=")
            if (node_labels or {}).get(key) == value:
                threshold = override
                break
        slo = NodeSLO(
            meta=ObjectMeta(name=node_name),
            threshold=dataclasses.replace(threshold),
            cpu_burst=dataclasses.replace(self.config.cpu_burst),
        )
        self._rendered[node_name] = slo
        return slo

    def get(self, node_name: str) -> Optional[NodeSLO]:
        return self._rendered.get(node_name)
