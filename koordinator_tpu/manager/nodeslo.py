"""NodeSLO controller: render per-node QoS strategy from cluster config.

Rebuild of ``pkg/slo-controller/nodeslo/``: the dynamic-config channel —
a cluster-level strategy (the reference's ``slo-controller-config``
ConfigMap, ``apis/configuration/slo_controller_config.go``) merged with
per-node overrides, rendered into one NodeSLO object per node that the
node agent enforces (qosmanager/runtimehooks).

Every NodeSLO strategy field renders (VERDICT r4 #6): threshold,
cpu-burst, system (kernel tuning), resctrl (RDT), blkio, per-QoS
resource knobs, and host applications — each with the reference's
per-node-selector override semantics (``nodeslo/resource_strategy.go``
getXStrategySpec: cluster default, then the FIRST matching nodeStrategies
entry wins).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Tuple

from ..api.types import (
    BlkIOStrategy,
    CPUBurstStrategy,
    NodeSLO,
    ObjectMeta,
    QoSClass,
    ResctrlStrategy,
    ResourceThresholdStrategy,
    SystemStrategy,
)


@dataclasses.dataclass
class SLOControllerConfig:
    """Cluster default strategies + per-node-label overrides.

    Override maps are keyed by a ``label=value`` selector; the first
    matching selector wins (the reference walks NodeStrategies in order,
    ``slo_controller_config.go`` NodeCfgProfile)."""

    threshold: ResourceThresholdStrategy = dataclasses.field(
        default_factory=lambda: ResourceThresholdStrategy(enable=True)
    )
    cpu_burst: CPUBurstStrategy = dataclasses.field(default_factory=CPUBurstStrategy)
    system: SystemStrategy = dataclasses.field(default_factory=SystemStrategy)
    resctrl: ResctrlStrategy = dataclasses.field(default_factory=ResctrlStrategy)
    blkio: BlkIOStrategy = dataclasses.field(default_factory=BlkIOStrategy)
    #: per-QoS-class resource QoS knobs (resource-qos-config)
    resource_qos: Dict[QoSClass, Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )
    #: out-of-band host daemons: (name, cgroup dir, qos class name)
    host_applications: List[Tuple[str, str, str]] = dataclasses.field(
        default_factory=list
    )
    #: node-label-selector -> override strategies (first match wins)
    node_overrides: Dict[str, ResourceThresholdStrategy] = dataclasses.field(
        default_factory=dict
    )
    cpu_burst_overrides: Dict[str, CPUBurstStrategy] = dataclasses.field(
        default_factory=dict
    )
    system_overrides: Dict[str, SystemStrategy] = dataclasses.field(
        default_factory=dict
    )
    resctrl_overrides: Dict[str, ResctrlStrategy] = dataclasses.field(
        default_factory=dict
    )
    blkio_overrides: Dict[str, BlkIOStrategy] = dataclasses.field(
        default_factory=dict
    )


def _select(default, overrides: Mapping[str, object], labels) -> object:
    """First matching selector wins. A selector is one or more
    comma-separated ``label=value`` pairs and matches only when the node
    carries EVERY pair (the reference matches the whole matchLabels
    set)."""
    labels = labels or {}
    for selector, override in overrides.items():
        pairs = [p for p in selector.split(",") if p]
        if pairs and all(
            labels.get(p.partition("=")[0]) == p.partition("=")[2]
            for p in pairs
        ):
            return override
    return default


class NodeSLOController:
    def __init__(self, config: Optional[SLOControllerConfig] = None):
        self.config = config or SLOControllerConfig()
        self._rendered: Dict[str, NodeSLO] = {}

    def render(
        self, node_name: str, node_labels: Optional[Mapping[str, str]] = None
    ) -> NodeSLO:
        import copy

        cfg = self.config
        # resctrl/blkio carry nested dicts — a shallow replace would let
        # one rendered SLO's mutation rewrite the cluster default and
        # every other node's SLO
        slo = NodeSLO(
            meta=ObjectMeta(name=node_name),
            threshold=dataclasses.replace(
                _select(cfg.threshold, cfg.node_overrides, node_labels)
            ),
            cpu_burst=dataclasses.replace(
                _select(cfg.cpu_burst, cfg.cpu_burst_overrides, node_labels)
            ),
            system=dataclasses.replace(
                _select(cfg.system, cfg.system_overrides, node_labels)
            ),
            resctrl=copy.deepcopy(
                _select(cfg.resctrl, cfg.resctrl_overrides, node_labels)
            ),
            blkio=copy.deepcopy(
                _select(cfg.blkio, cfg.blkio_overrides, node_labels)
            ),
            resource_qos={
                qos: dict(knobs) for qos, knobs in cfg.resource_qos.items()
            },
            host_applications=list(cfg.host_applications),
        )
        self._rendered[node_name] = slo
        return slo

    def get(self, node_name: str) -> Optional[NodeSLO]:
        return self._rendered.get(node_name)

    # ---- dynamic-config ingestion (the ConfigMap channel) ----

    #: data keys the reference ConfigMap carries
    #: (``slo_controller_config.go``: resource-threshold-config,
    #: cpu-burst-config, system-config, resource-qos-config, ...)
    _KEYS = (
        "resource-threshold-config",
        "cpu-burst-config",
        "system-config",
        "resource-qos-config",
        "host-application-config",
    )

    def apply_configmap(self, data: Mapping[str, Mapping]) -> None:
        """Re-render the cluster strategies from parsed
        slo-controller-config blobs (see
        ``api.yaml_loader.load_slo_controller_config``). Each present
        blob fully replaces its family's nodeStrategies overrides — a
        deleted entry must stop applying (the reference re-renders from
        the whole current ConfigMap on every update); absent fields keep
        the current cluster value (unmarshal-over-defaults)."""
        thr = data.get("resource-threshold-config")
        if isinstance(thr, Mapping):
            cluster = thr.get("clusterStrategy", thr)
            self.config.threshold = _merge_threshold(
                self.config.threshold, cluster
            )
            self.config.node_overrides = {}
            for entry in thr.get("nodeStrategies", []) or []:
                sel = _selector_of(entry)
                if sel:
                    self.config.node_overrides[sel] = _merge_threshold(
                        self.config.threshold, entry
                    )
        burst = data.get("cpu-burst-config")
        if isinstance(burst, Mapping):
            cluster = burst.get("clusterStrategy", burst)
            self.config.cpu_burst = _merge_burst(
                self.config.cpu_burst, cluster
            )
            self.config.cpu_burst_overrides = {}
            for entry in burst.get("nodeStrategies", []) or []:
                sel = _selector_of(entry)
                if sel:
                    self.config.cpu_burst_overrides[sel] = _merge_burst(
                        self.config.cpu_burst, entry
                    )
        system = data.get("system-config")
        if isinstance(system, Mapping):
            cluster = system.get("clusterStrategy", system)
            self.config.system = _merge_system(self.config.system, cluster)
            self.config.system_overrides = {}
            for entry in system.get("nodeStrategies", []) or []:
                sel = _selector_of(entry)
                if sel:
                    self.config.system_overrides[sel] = _merge_system(
                        self.config.system, entry
                    )
        qos = data.get("resource-qos-config")
        if isinstance(qos, Mapping):
            self.config.resource_qos = _parse_resource_qos(
                qos.get("clusterStrategy", qos)
            )
        hostapps = data.get("host-application-config")
        if isinstance(hostapps, Mapping):
            apps = []
            for app in hostapps.get("applications", []) or []:
                apps.append(
                    (
                        str(app.get("name", "")),
                        str((app.get("cgroupPath") or {}).get("relativePath", "")),
                        str(app.get("qos", "LS")),
                    )
                )
            self.config.host_applications = apps


def _selector_of(entry: Mapping) -> str:
    """The FULL matchLabels set as a canonical comma-joined selector —
    dropping pairs would over-match nodes."""
    sel = (entry.get("nodeSelector") or {}).get("matchLabels") or {}
    return ",".join(f"{k}={v}" for k, v in sorted(sel.items()))


def _merge_threshold(
    base: ResourceThresholdStrategy, raw: Mapping
) -> ResourceThresholdStrategy:
    return ResourceThresholdStrategy(
        enable=bool(raw.get("enable", base.enable)),
        cpu_suppress_threshold_percent=float(
            raw.get(
                "cpuSuppressThresholdPercent",
                base.cpu_suppress_threshold_percent,
            )
        ),
        cpu_evict_be_usage_threshold_percent=float(
            raw.get(
                "cpuEvictBEUsageThresholdPercent",
                base.cpu_evict_be_usage_threshold_percent,
            )
        ),
        memory_evict_threshold_percent=float(
            raw.get(
                "memoryEvictThresholdPercent",
                base.memory_evict_threshold_percent,
            )
        ),
        memory_evict_lower_percent=raw.get(
            "memoryEvictLowerPercent", base.memory_evict_lower_percent
        ),
    )


def _merge_burst(base: CPUBurstStrategy, raw: Mapping) -> CPUBurstStrategy:
    return CPUBurstStrategy(
        policy=str(raw.get("policy", base.policy)),
        cpu_burst_percent=float(
            raw.get("cpuBurstPercent", base.cpu_burst_percent)
        ),
        cfs_quota_burst_percent=float(
            raw.get("cfsQuotaBurstPercent", base.cfs_quota_burst_percent)
        ),
    )


def _parse_resource_qos(raw: Mapping) -> Dict[QoSClass, Dict[str, float]]:
    """resource-qos-config clusterStrategy: the reference keys per-class
    blocks as lsrClass/lsClass/beClass/systemClass
    (``slo_controller_config.go`` ResourceQOSCfg); nested knob objects
    flatten to dotted numeric keys (``memoryQoS.wmarkRatio``)."""
    out: Dict[QoSClass, Dict[str, float]] = {}
    for key, block in raw.items():
        name = str(key)
        if name.endswith("Class"):
            name = name[: -len("Class")]
        try:
            qos = QoSClass.parse(name.upper())
        except (ValueError, KeyError):
            continue
        if qos == QoSClass.NONE or not isinstance(block, Mapping):
            continue
        knobs: Dict[str, float] = {}

        def flatten(prefix: str, obj: Mapping) -> None:
            for k, v in obj.items():
                path = f"{prefix}.{k}" if prefix else str(k)
                if isinstance(v, Mapping):
                    flatten(path, v)
                else:
                    try:
                        knobs[path] = float(v)
                    except (TypeError, ValueError):
                        continue

        flatten("", block)
        out[qos] = knobs
    return out


def _merge_system(base: SystemStrategy, raw: Mapping) -> SystemStrategy:
    return SystemStrategy(
        enable=bool(raw.get("enable", base.enable)),
        min_free_kbytes_factor=float(
            raw.get("minFreeKbytesFactor", base.min_free_kbytes_factor)
        ),
        watermark_scale_factor=float(
            raw.get("watermarkScaleFactor", base.watermark_scale_factor)
        ),
        memcg_reap_background=int(
            raw.get("memcgReapBackGround", base.memcg_reap_background)
        ),
    )
