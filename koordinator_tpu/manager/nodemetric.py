"""NodeMetric spec controller.

Rebuild of ``pkg/slo-controller/nodemetric/nodemetric_controller.go``: for
every node, ensure a NodeMetric object exists whose *spec* carries the
collect policy (report interval / aggregate window / node-memory collect
policy) rendered from the cluster config — the node agent fills the
*status* (see :mod:`koordinator_tpu.koordlet.daemon`). Defaults mirror
``states_nodemetric.go:61-66``: 60 s report, 300 s aggregate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Optional

from ..api.types import NodeMetric, ObjectMeta


@dataclasses.dataclass
class NodeMetricCollectPolicy:
    report_interval_s: float = 60.0
    aggregate_duration_s: float = 300.0
    #: "usageWithoutPageCache" | "usageWithPageCache" (reference
    #: nodemetric spec NodeMemoryCollectPolicy)
    node_memory_policy: str = "usageWithoutPageCache"


class NodeMetricController:
    """Reconciles one NodeMetric per node; deletes orphans."""

    def __init__(self, policy: Optional[NodeMetricCollectPolicy] = None):
        self.policy = policy or NodeMetricCollectPolicy()
        self.metrics: Dict[str, NodeMetric] = {}

    def reconcile(self, node_names: Iterable[str]) -> Dict[str, NodeMetric]:
        names = set(node_names)
        for name in names:
            nm = self.metrics.get(name)
            if nm is None:
                nm = NodeMetric(meta=ObjectMeta(name=name))
                self.metrics[name] = nm
            nm.report_interval_s = self.policy.report_interval_s
            nm.aggregate_window_s = self.policy.aggregate_duration_s
        for orphan in [n for n in self.metrics if n not in names]:
            del self.metrics[orphan]
        return self.metrics

    def observe(self, report: NodeMetric) -> None:
        """Accept a koordlet status report (the CRD status write path)."""
        self.metrics[report.meta.name] = report
