"""ElasticQuotaProfile controller: node-selector-scoped quota trees.

Rebuild of ``pkg/quota-controller/profile/profile_controller.go:62-273``:
each profile selects nodes by label and maintains a root ElasticQuota whose
min/max equal the selected nodes' summed allocatable (optionally scaled by
a resource ratio, ``DecorateResourceByResourceRatio``). This is how
multi-pool clusters get one quota tree per hardware pool.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional

from ..api.types import ElasticQuota, ElasticQuotaProfile, Node, ObjectMeta
from .profile import _selector_matches as _matches

#: annotation holding the ratio applied to the summed totals
ANNOTATION_RESOURCE_RATIO = "quota.koordinator.sh/resource-ratio"


class QuotaProfileController:
    """Reconciles root ElasticQuotas from profiles + the node inventory."""

    def __init__(self) -> None:
        self.profiles: Dict[str, ElasticQuotaProfile] = {}

    def upsert(self, profile: ElasticQuotaProfile) -> None:
        self.profiles[profile.meta.name] = profile

    def remove(self, name: str) -> None:
        self.profiles.pop(name, None)

    def reconcile(self, nodes: Iterable[Node]) -> List[ElasticQuota]:
        """One pass over the node inventory → updated root quotas."""
        node_list = list(nodes)
        out: List[ElasticQuota] = []
        for profile in self.profiles.values():
            selected = [
                n
                for n in node_list
                if _matches(profile.node_selector, n.meta.labels)
                and not n.unschedulable
            ]
            total: Dict[str, float] = {}
            for n in selected:
                for key, val in n.status.allocatable.items():
                    if profile.resource_keys and key not in profile.resource_keys:
                        continue
                    total[key] = total.get(key, 0.0) + val
            ratio = 1.0
            raw = profile.meta.annotations.get(ANNOTATION_RESOURCE_RATIO)
            if raw:
                try:
                    ratio = min(max(float(raw), 0.0), 1.0)
                except ValueError:
                    ratio = 1.0
            if ratio != 1.0:
                total = {k: v * ratio for k, v in total.items()}
            eq = ElasticQuota(
                meta=ObjectMeta(
                    name=profile.quota_name,
                    labels=dict(profile.quota_labels),
                ),
                min=dict(total),
                max=dict(total),
                is_parent=True,
                tree_id=profile.meta.name,
            )
            out.append(eq)
        return out
