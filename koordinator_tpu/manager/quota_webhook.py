"""ElasticQuota webhook: tree structural invariants + quota admission.

Rebuild of ``pkg/webhook/elasticquota/`` (``quota_topology.go``,
``quota_topology_check.go:39-120``) and the quota admission evaluator
(``pkg/webhook/quotaevaluate/``): validates quota CRUD against the tree's
structural invariants before the scheduler's GroupQuotaManager ever sees
the object, and (optionally, ``EnableQuotaAdmission``) rejects pods whose
requests exceed quota runtime at admission time instead of letting them
queue forever.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional

from ..api.types import ElasticQuota, Pod
from ..scheduler.plugins.elasticquota import GroupQuotaManager, quota_name_of


class QuotaTopologyValidator:
    """Mirrors the reference's in-webhook shadow topology
    (``quota_topology.go``: the webhook maintains its own quotaInfo map so
    validation never races the scheduler's)."""

    def __init__(self) -> None:
        self.quotas: Dict[str, ElasticQuota] = {}
        #: quota name -> number of pods currently bound to it
        self.pod_counts: Dict[str, int] = {}

    # ---- self-item checks (quota_topology_check.go:39-90) ----

    @staticmethod
    def validate_self(eq: ElasticQuota) -> List[str]:
        errors: List[str] = []
        for key, val in eq.max.items():
            if val < 0:
                errors.append(f"{eq.meta.name}: max[{key}] < 0")
        for key, val in eq.min.items():
            if val < 0:
                errors.append(f"{eq.meta.name}: min[{key}] < 0")
            if key in eq.max and val > eq.max[key]:
                errors.append(
                    f"{eq.meta.name}: min[{key}]={val} > max[{key}]={eq.max[key]}"
                )
            if key not in eq.max:
                errors.append(
                    f"{eq.meta.name}: min key {key} not included in max"
                )
        for key, val in eq.shared_weight.items():
            if val < 0:
                errors.append(f"{eq.meta.name}: sharedWeight[{key}] < 0")
        return errors

    # ---- topology checks (quota_topology_check.go:92-120) ----

    def validate_create(self, eq: ElasticQuota) -> List[str]:
        errors = self.validate_self(eq)
        name = eq.meta.name
        if name in self.quotas:
            errors.append(f"quota {name} already exists")
        errors += self._check_parent(eq)
        errors += self._check_min_sum(eq, exclude=None)
        return errors

    def validate_update(self, eq: ElasticQuota) -> List[str]:
        errors = self.validate_self(eq)
        old = self.quotas.get(eq.meta.name)
        if old is None:
            errors.append(f"quota {eq.meta.name} not found")
            return errors
        if old.tree_id and old.tree_id != eq.tree_id:
            # checkTreeID: a quota can never move between (or leave) trees
            errors.append(
                f"quota {eq.meta.name}: tree id is immutable "
                f"({old.tree_id} -> {eq.tree_id or '<empty>'})"
            )
        if old.is_parent and not eq.is_parent and self._children_of(eq.meta.name):
            # checkIsParentChange: cannot demote a parent that has children
            errors.append(
                f"quota {eq.meta.name}: cannot become leaf while it has children"
            )
        errors += self._check_parent(eq)
        errors += self._check_min_sum(eq, exclude=eq.meta.name)
        # shrinking a parent's min must still cover its children's min sum
        child_sum: Dict[str, float] = {}
        for kid in self._children_of(eq.meta.name):
            for key, val in self.quotas[kid].min.items():
                child_sum[key] = child_sum.get(key, 0.0) + val
        for key, total in child_sum.items():
            if total > eq.min.get(key, 0.0) + 1e-9:
                errors.append(
                    f"quota {eq.meta.name}: children min sum {total} exceeds "
                    f"new min {eq.min.get(key, 0.0)} for {key}"
                )
        return errors

    def validate_delete(self, name: str) -> List[str]:
        errors: List[str] = []
        if self._children_of(name):
            errors.append(f"quota {name} still has child quotas")
        if self.pod_counts.get(name, 0) > 0:
            errors.append(f"quota {name} still has bound pods")
        return errors

    def _check_parent(self, eq: ElasticQuota) -> List[str]:
        """checkParentQuotaInfo: parent must exist, be marked is-parent,
        share the tree id, and the edge must not create a cycle."""
        errors: List[str] = []
        if not eq.parent:
            return errors
        parent = self.quotas.get(eq.parent)
        if parent is None:
            errors.append(f"quota {eq.meta.name}: parent {eq.parent} not found")
            return errors
        if not parent.is_parent:
            errors.append(
                f"quota {eq.meta.name}: parent {eq.parent} is not marked is-parent"
            )
        if parent.tree_id and eq.tree_id and parent.tree_id != eq.tree_id:
            errors.append(
                f"quota {eq.meta.name}: tree id {eq.tree_id} differs from "
                f"parent's {parent.tree_id}"
            )
        seen = {eq.meta.name}
        cursor: Optional[str] = eq.parent
        while cursor:
            if cursor in seen:
                errors.append(f"quota {eq.meta.name}: parent chain has a cycle")
                break
            seen.add(cursor)
            cur = self.quotas.get(cursor)
            cursor = cur.parent if cur else None
        return errors

    def _check_min_sum(self, eq: ElasticQuota, exclude: Optional[str]) -> List[str]:
        """checkMinQuotaValidate: Σ child min ≤ parent min per dimension."""
        errors: List[str] = []
        if not eq.parent:
            return errors
        parent = self.quotas.get(eq.parent)
        if parent is None:
            return errors
        sums: Dict[str, float] = dict(eq.min)
        for sib in self._children_of(eq.parent):
            if sib == (exclude or eq.meta.name):
                continue
            for key, val in self.quotas[sib].min.items():
                sums[key] = sums.get(key, 0.0) + val
        for key, total in sums.items():
            pmin = parent.min.get(key, 0.0)
            if total > pmin + 1e-9:
                errors.append(
                    f"quota {eq.meta.name}: children min sum {total} exceeds "
                    f"parent {eq.parent} min {pmin} for {key}"
                )
        return errors

    def _children_of(self, name: str) -> List[str]:
        return [q.meta.name for q in self.quotas.values() if q.parent == name]

    # ---- state mirror ----

    def admit(self, eq: ElasticQuota, is_update: bool = False) -> List[str]:
        errors = (
            self.validate_update(eq) if is_update else self.validate_create(eq)
        )
        if not errors:
            self.quotas[eq.meta.name] = eq
        return errors

    def delete(self, name: str) -> List[str]:
        errors = self.validate_delete(name)
        if not errors:
            self.quotas.pop(name, None)
        return errors


class QuotaAdmissionEvaluator:
    """Pod-time quota admission (``pkg/webhook/quotaevaluate/``,
    gated by the ``EnableQuotaAdmission`` feature gate): used + request ≤
    runtime along the pod's quota chain, checked against the scheduler's
    GroupQuotaManager."""

    def __init__(
        self, manager: GroupQuotaManager, enabled: Optional[bool] = None
    ):
        self.manager = manager
        #: None = follow the feature gate live (queried per admit, so a
        #: --feature-gates change after wiring takes effect immediately)
        self.enabled = enabled

    @property
    def _enabled_now(self) -> bool:
        if self.enabled is not None:
            return self.enabled
        from ..utils.features import MANAGER_GATES

        return MANAGER_GATES.enabled("EnableQuotaAdmission")

    def admit(self, pod: Pod) -> List[str]:
        if not self._enabled_now:
            return []
        quota = quota_name_of(pod)
        if quota is None or self.manager.index_of(quota) is None:
            return []
        if not self.manager.has_headroom(quota, pod.spec.requests):
            return [
                f"pod {pod.meta.uid}: quota {quota} has no headroom for "
                f"{pod.spec.requests}"
            ]
        return []
