"""slo-controller-config ConfigMap validating webhook.

Rebuild of ``pkg/webhook/cm/`` (``validating_handler.go`` +
``plugins/sloconfig/``): on a ConfigMap update, every *changed, non-empty*
config key is checked — JSON must parse, values must sit in the ranges the
reference's struct-validator tags declare (``apis/slo/v1alpha1/
nodeslo_types.go``, ``apis/configuration/slo_controller_config.go``), and
per-key ``nodeStrategies``/``nodeConfigs`` profiles must carry unique
names, non-empty selectors, and must not overlap (two profiles whose
selectors can match the same node make the rendered NodeSLO ambiguous,
``checker.go:96-140`` CreateNodeConfigProfileChecker +
``selector.go`` NodeSelectorOverlap).
"""

from __future__ import annotations

import json
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

# keys in the configmap (slo_controller_config.go:26-37)
COLOCATION_CONFIG_KEY = "colocation-config"
RESOURCE_THRESHOLD_CONFIG_KEY = "resource-threshold-config"
RESOURCE_QOS_CONFIG_KEY = "resource-qos-config"
CPU_BURST_CONFIG_KEY = "cpu-burst-config"
SYSTEM_CONFIG_KEY = "system-config"
HOST_APPLICATION_CONFIG_KEY = "host-application-config"
CPU_NORMALIZATION_CONFIG_KEY = "cpu-normalization-config"
RESOURCE_AMPLIFICATION_CONFIG_KEY = "resource-amplification-config"

#: (field, lo, hi) inclusive ranges per config key — the reference's
#: validator tags (nodeslo_types.go; None bound = unbounded)
_RANGES: Dict[str, Sequence[Tuple[str, Optional[float], Optional[float]]]] = {
    COLOCATION_CONFIG_KEY: [
        ("cpuReclaimThresholdPercent", 0, 100),
        ("memoryReclaimThresholdPercent", 0, 100),
        ("metricAggregateDurationSeconds", 1, None),
        ("metricReportIntervalSeconds", 1, None),
        ("degradeTimeMinutes", 1, None),
        ("updateTimeThresholdSeconds", 1, None),
        ("midCPUThresholdPercent", 0, 100),
        ("midMemoryThresholdPercent", 0, 100),
        ("midUnallocatedPercent", 0, 100),
    ],
    RESOURCE_THRESHOLD_CONFIG_KEY: [
        ("cpuSuppressThresholdPercent", 0, 100),
        ("cpuSuppressMinPercent", 0, 100),
        ("memoryEvictThresholdPercent", 0, 100),
        ("memoryEvictLowerPercent", 0, 100),
        ("cpuEvictBESatisfactionUpperPercent", 0, 100),
        ("cpuEvictBESatisfactionLowerPercent", 0, 100),
        ("cpuEvictBEUsageThresholdPercent", 0, 100),
    ],
    CPU_BURST_CONFIG_KEY: [
        ("cpuBurstPercent", 1, 10000),
        ("cfsQuotaBurstPercent", 100, None),
        ("sharePoolThresholdPercent", 0, 100),
    ],
    SYSTEM_CONFIG_KEY: [
        ("minFreeKbytesFactor", 1, None),
        ("watermarkScaleFactor", 1, 400),
        ("memcgReapBackGround", 0, 1),
    ],
    RESOURCE_QOS_CONFIG_KEY: [],  # nested per-class checks below
}

#: ordered-pair constraints: field a must be < field b when both set
#: (ltfield/gtfield tags)
_ORDERINGS: Dict[str, Sequence[Tuple[str, str]]] = {
    RESOURCE_THRESHOLD_CONFIG_KEY: [
        ("memoryEvictLowerPercent", "memoryEvictThresholdPercent"),
        (
            "cpuEvictBESatisfactionLowerPercent",
            "cpuEvictBESatisfactionUpperPercent",
        ),
    ],
}

#: resource-qos nested leaf ranges (cpuQOS/memoryQOS/resctrlQOS fields)
_QOS_LEAF_RANGES: Sequence[Tuple[str, Optional[float], Optional[float]]] = [
    ("groupIdentity", -1, 2),
    ("schedIdle", 0, 1),
    ("minLimitPercent", 0, 100),
    ("lowLimitPercent", 0, 100),
    ("throttlingPercent", 0, 100),
    ("wmarkRatio", 0, 100),
    ("wmarkScalePermill", 1, 1000),
    ("wmarkMinAdj", -25, 50),
    ("priorityEnable", 0, 1),
    ("priority", 0, 12),
    ("oomKillGroup", 0, 1),
    ("catRangeStartPercent", 0, 100),
    ("catRangeEndPercent", 0, 100),
    ("mbaPercent", 0, 100),
]


def _check_ranges(
    obj: Mapping, rules, path: str, errors: List[str]
) -> None:
    for field, lo, hi in rules:
        if field not in obj or obj[field] is None:
            continue
        try:
            val = float(obj[field])
        except (TypeError, ValueError):
            errors.append(f"{path}.{field}: not a number: {obj[field]!r}")
            continue
        if lo is not None and val < lo:
            errors.append(f"{path}.{field}: {val:g} below minimum {lo:g}")
        if hi is not None and val > hi:
            errors.append(f"{path}.{field}: {val:g} above maximum {hi:g}")


def _check_orderings(obj: Mapping, rules, path: str, errors: List[str]) -> None:
    for low_field, high_field in rules:
        lo, hi = obj.get(low_field), obj.get(high_field)
        if lo is None or hi is None:
            continue
        try:
            if float(lo) >= float(hi):
                errors.append(
                    f"{path}.{low_field}: {lo} must be below {high_field} {hi}"
                )
        except (TypeError, ValueError):
            pass  # range check already reported it


def _check_qos_classes(cfg: Mapping, path: str, errors: List[str]) -> None:
    for cls in ("lsrClass", "lsClass", "beClass", "systemClass", "cgroupRoot"):
        class_cfg = cfg.get(cls)
        if not isinstance(class_cfg, Mapping):
            continue
        for sub in ("cpuQOS", "memoryQOS", "resctrlQOS", "blkioQOS", "networkQOS"):
            sub_cfg = class_cfg.get(sub)
            if isinstance(sub_cfg, Mapping):
                _check_ranges(
                    sub_cfg, _QOS_LEAF_RANGES, f"{path}.{cls}.{sub}", errors
                )
                _check_orderings(
                    sub_cfg,
                    [("catRangeStartPercent", "catRangeEndPercent")],
                    f"{path}.{cls}.{sub}",
                    errors,
                )


def _selectors_overlap(a: Mapping[str, str], b: Mapping[str, str]) -> bool:
    """Two matchLabels selectors can match the same node unless they
    *conflict* — demand different values for a shared key (the reference's
    NodeSelectorOverlap uses the same requirement-conflict test)."""
    for key, val in a.items():
        if key in b and b[key] != val:
            return False
    return True


def _check_profiles(cfg: Mapping, key: str, path: str, errors: List[str]) -> None:
    profiles = cfg.get("nodeStrategies") or cfg.get("nodeConfigs") or []
    if not isinstance(profiles, list):
        errors.append(f"{path}: nodeStrategies must be a list")
        return
    seen_names: Dict[str, int] = {}
    parsed: List[Tuple[str, Mapping[str, str]]] = []
    for i, prof in enumerate(profiles):
        if not isinstance(prof, Mapping):
            errors.append(f"{path}[{i}]: not an object")
            continue
        name = prof.get("name") or f"#{i}"
        if name in seen_names:
            errors.append(f"{path}[{i}]: duplicate profile name {name!r}")
        seen_names[name] = i
        selector = (prof.get("nodeSelector") or {}).get("matchLabels") or {}
        has_exprs = bool((prof.get("nodeSelector") or {}).get("matchExpressions"))
        if not selector and not has_exprs:
            errors.append(
                f"{path}[{i}] ({name}): nodeSelector must not be empty"
            )
            continue
        parsed.append((name, dict(selector)))
        # per-profile strategy values obey the same ranges
        _check_ranges(prof, _RANGES.get(key, ()), f"{path}[{i}]", errors)
        _check_orderings(prof, _ORDERINGS.get(key, ()), f"{path}[{i}]", errors)
    for i in range(len(parsed)):
        for j in range(i + 1, len(parsed)):
            if _selectors_overlap(parsed[i][1], parsed[j][1]):
                errors.append(
                    f"{path}: profiles {parsed[i][0]!r} and {parsed[j][0]!r} "
                    "have overlapping node selectors"
                )


def validate_slo_configmap(
    new_data: Mapping[str, str],
    old_data: Optional[Mapping[str, str]] = None,
) -> List[str]:
    """Errors for the changed keys of a slo-controller-config update;
    empty list = admit (``validating_handler.go`` Handle)."""
    errors: List[str] = []
    for key in (
        COLOCATION_CONFIG_KEY,
        RESOURCE_THRESHOLD_CONFIG_KEY,
        RESOURCE_QOS_CONFIG_KEY,
        CPU_BURST_CONFIG_KEY,
        SYSTEM_CONFIG_KEY,
        HOST_APPLICATION_CONFIG_KEY,
        CPU_NORMALIZATION_CONFIG_KEY,
        RESOURCE_AMPLIFICATION_CONFIG_KEY,
    ):
        raw = new_data.get(key, "")
        if not raw:
            continue
        if old_data is not None and old_data.get(key, "") == raw:
            continue  # unchanged keys are not re-validated (CommonChecker)
        try:
            cfg = json.loads(raw)
        except (ValueError, TypeError) as e:
            errors.append(f"{key}: invalid JSON: {e}")
            continue
        if not isinstance(cfg, Mapping):
            errors.append(f"{key}: must be a JSON object")
            continue
        _check_ranges(cfg, _RANGES.get(key, ()), key, errors)
        _check_orderings(cfg, _ORDERINGS.get(key, ()), key, errors)
        if key == RESOURCE_QOS_CONFIG_KEY:
            _check_qos_classes(cfg, key, errors)
            for prof in cfg.get("nodeStrategies") or []:
                if isinstance(prof, Mapping):
                    _check_qos_classes(prof, f"{key}.nodeStrategies", errors)
        _check_profiles(cfg, key, key, errors)
    return errors


def node_profile_conflicts(
    new_data: Mapping[str, str], node_labels: Mapping[str, str]
) -> List[str]:
    """ExistNodeConflict (``checker.go:142-160``): for one concrete node,
    more than one profile of a config key matching it is a conflict."""
    errors: List[str] = []
    for key, raw in new_data.items():
        if not raw:
            continue
        try:
            cfg = json.loads(raw)
        except (ValueError, TypeError):
            continue
        if not isinstance(cfg, Mapping):
            continue
        matches = []
        for prof in cfg.get("nodeStrategies") or cfg.get("nodeConfigs") or []:
            if not isinstance(prof, Mapping):
                continue
            selector = (prof.get("nodeSelector") or {}).get("matchLabels") or {}
            if selector and all(
                node_labels.get(k) == v for k, v in selector.items()
            ):
                matches.append(prof.get("name") or "?")
        if len(matches) > 1:
            errors.append(
                f"{key}: node matches multiple profiles {matches}"
            )
    return errors
