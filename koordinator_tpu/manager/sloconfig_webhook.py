"""slo-controller-config ConfigMap validating webhook.

Rebuild of ``pkg/webhook/cm/`` (``validating_handler.go`` +
``plugins/sloconfig/``): on a ConfigMap update, every *changed, non-empty*
config key is checked — JSON must parse, values must sit in the ranges the
reference's struct-validator tags declare (``apis/slo/v1alpha1/
nodeslo_types.go``, ``apis/configuration/slo_controller_config.go``), and
per-key ``nodeStrategies``/``nodeConfigs`` profiles must carry unique
names, non-empty selectors, and must not overlap (two profiles whose
selectors can match the same node make the rendered NodeSLO ambiguous,
``checker.go:96-140`` CreateNodeConfigProfileChecker +
``selector.go`` NodeSelectorOverlap).
"""

from __future__ import annotations

import json
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

# keys in the configmap (slo_controller_config.go:26-37)
COLOCATION_CONFIG_KEY = "colocation-config"
RESOURCE_THRESHOLD_CONFIG_KEY = "resource-threshold-config"
RESOURCE_QOS_CONFIG_KEY = "resource-qos-config"
CPU_BURST_CONFIG_KEY = "cpu-burst-config"
SYSTEM_CONFIG_KEY = "system-config"
HOST_APPLICATION_CONFIG_KEY = "host-application-config"
CPU_NORMALIZATION_CONFIG_KEY = "cpu-normalization-config"
RESOURCE_AMPLIFICATION_CONFIG_KEY = "resource-amplification-config"

#: (field, lo, hi) inclusive ranges per config key — the reference's
#: validator tags (nodeslo_types.go; None bound = unbounded)
_RANGES: Dict[str, Sequence[Tuple[str, Optional[float], Optional[float]]]] = {
    COLOCATION_CONFIG_KEY: [
        ("cpuReclaimThresholdPercent", 0, 100),
        ("memoryReclaimThresholdPercent", 0, 100),
        ("metricAggregateDurationSeconds", 1, None),
        ("metricReportIntervalSeconds", 1, None),
        ("degradeTimeMinutes", 1, None),
        ("updateTimeThresholdSeconds", 1, None),
        ("midCPUThresholdPercent", 0, 100),
        ("midMemoryThresholdPercent", 0, 100),
        ("midUnallocatedPercent", 0, 100),
    ],
    RESOURCE_THRESHOLD_CONFIG_KEY: [
        ("cpuSuppressThresholdPercent", 0, 100),
        ("cpuSuppressMinPercent", 0, 100),
        ("memoryEvictThresholdPercent", 0, 100),
        ("memoryEvictLowerPercent", 0, 100),
        ("cpuEvictBESatisfactionUpperPercent", 0, 100),
        ("cpuEvictBESatisfactionLowerPercent", 0, 100),
        ("cpuEvictBEUsageThresholdPercent", 0, 100),
    ],
    CPU_BURST_CONFIG_KEY: [
        ("cpuBurstPercent", 1, 10000),
        ("cfsQuotaBurstPercent", 100, None),
        ("sharePoolThresholdPercent", 0, 100),
    ],
    SYSTEM_CONFIG_KEY: [
        ("minFreeKbytesFactor", 1, None),
        ("watermarkScaleFactor", 1, 400),
        ("memcgReapBackGround", 0, 1),
    ],
    RESOURCE_QOS_CONFIG_KEY: [],  # nested per-class checks below
}

#: ordered-pair constraints: field a must be < field b when both set
#: (ltfield/gtfield tags)
_ORDERINGS: Dict[str, Sequence[Tuple[str, str]]] = {
    RESOURCE_THRESHOLD_CONFIG_KEY: [
        ("memoryEvictLowerPercent", "memoryEvictThresholdPercent"),
        (
            "cpuEvictBESatisfactionLowerPercent",
            "cpuEvictBESatisfactionUpperPercent",
        ),
    ],
}

#: resource-qos nested leaf ranges (cpuQOS/memoryQOS/resctrlQOS fields)
_QOS_LEAF_RANGES: Sequence[Tuple[str, Optional[float], Optional[float]]] = [
    ("groupIdentity", -1, 2),
    ("schedIdle", 0, 1),
    ("minLimitPercent", 0, 100),
    ("lowLimitPercent", 0, 100),
    ("throttlingPercent", 0, 100),
    ("wmarkRatio", 0, 100),
    ("wmarkScalePermill", 1, 1000),
    ("wmarkMinAdj", -25, 50),
    ("priorityEnable", 0, 1),
    ("priority", 0, 12),
    ("oomKillGroup", 0, 1),
    ("catRangeStartPercent", 0, 100),
    ("catRangeEndPercent", 0, 100),
    ("mbaPercent", 0, 100),
]


def _check_ranges(
    obj: Mapping, rules, path: str, errors: List[str]
) -> None:
    for field, lo, hi in rules:
        if field not in obj or obj[field] is None:
            continue
        try:
            val = float(obj[field])
        except (TypeError, ValueError):
            errors.append(f"{path}.{field}: not a number: {obj[field]!r}")
            continue
        if lo is not None and val < lo:
            errors.append(f"{path}.{field}: {val:g} below minimum {lo:g}")
        if hi is not None and val > hi:
            errors.append(f"{path}.{field}: {val:g} above maximum {hi:g}")


def _check_orderings(obj: Mapping, rules, path: str, errors: List[str]) -> None:
    for low_field, high_field in rules:
        lo, hi = obj.get(low_field), obj.get(high_field)
        if lo is None or hi is None:
            continue
        try:
            if float(lo) >= float(hi):
                errors.append(
                    f"{path}.{low_field}: {lo} must be below {high_field} {hi}"
                )
        except (TypeError, ValueError):
            pass  # range check already reported it


def _check_qos_classes(cfg: Mapping, path: str, errors: List[str]) -> None:
    for cls in ("lsrClass", "lsClass", "beClass", "systemClass", "cgroupRoot"):
        class_cfg = cfg.get(cls)
        if not isinstance(class_cfg, Mapping):
            continue
        for sub in ("cpuQOS", "memoryQOS", "resctrlQOS", "blkioQOS", "networkQOS"):
            sub_cfg = class_cfg.get(sub)
            if isinstance(sub_cfg, Mapping):
                _check_ranges(
                    sub_cfg, _QOS_LEAF_RANGES, f"{path}.{cls}.{sub}", errors
                )
                _check_orderings(
                    sub_cfg,
                    [("catRangeStartPercent", "catRangeEndPercent")],
                    f"{path}.{cls}.{sub}",
                    errors,
                )


def _selector_requirements(selector: Mapping) -> List[Tuple[str, str, frozenset]]:
    """Lower a nodeSelector to (key, operator, values) requirements —
    matchLabels become In requirements, matchExpressions pass through
    (the reference's NodeSelectorOverlap expands expressions the same
    way, ``pkg/webhook/cm/plugins/sloconfig/common_check.go``)."""
    reqs: List[Tuple[str, str, frozenset]] = []
    for k, v in (selector.get("matchLabels") or {}).items():
        reqs.append((str(k), "In", frozenset([str(v)])))
    for expr in selector.get("matchExpressions") or []:
        if not isinstance(expr, Mapping):
            continue
        key, op = expr.get("key"), expr.get("operator")
        if not key or not op:
            continue
        vals = frozenset(str(x) for x in expr.get("values") or [])
        reqs.append((str(key), str(op), vals))
    return reqs


def _requirements_conflict(
    a: List[Tuple[str, str, frozenset]], b: List[Tuple[str, str, frozenset]]
) -> bool:
    """True when no node's labels can satisfy both requirement sets
    (k8s label-selector semantics: NotIn also matches an absent key,
    In/Exists require the key present)."""
    by_key: Dict[str, List[Tuple[str, frozenset]]] = {}
    for k, op, vals in a + b:
        by_key.setdefault(k, []).append((op, vals))
    for items in by_key.values():
        ins = [v for op, v in items if op == "In"]
        notins = [v for op, v in items if op == "NotIn"]
        absent = any(op == "DoesNotExist" for op, _ in items)
        present = bool(ins) or any(op == "Exists" for op, _ in items)
        if absent and present:
            return True
        if ins:
            candidates = frozenset.intersection(*ins)
            for nv in notins:
                candidates -= nv
            if not candidates:
                return True
    return False


def _selectors_overlap(
    a: List[Tuple[str, str, frozenset]], b: List[Tuple[str, str, frozenset]]
) -> bool:
    """Two node selectors can match the same node unless their merged
    requirements conflict (the reference's NodeSelectorOverlap)."""
    return not _requirements_conflict(a, b)


def _check_profiles(cfg: Mapping, key: str, path: str, errors: List[str]) -> None:
    profiles = cfg.get("nodeStrategies") or cfg.get("nodeConfigs") or []
    if not isinstance(profiles, list):
        errors.append(f"{path}: nodeStrategies must be a list")
        return
    seen_names: Dict[str, int] = {}
    parsed: List[Tuple[str, List[Tuple[str, str, frozenset]]]] = []
    for i, prof in enumerate(profiles):
        if not isinstance(prof, Mapping):
            errors.append(f"{path}[{i}]: not an object")
            continue
        name = prof.get("name") or f"#{i}"
        if name in seen_names:
            errors.append(f"{path}[{i}]: duplicate profile name {name!r}")
        seen_names[name] = i
        node_selector = prof.get("nodeSelector") or {}
        reqs = _selector_requirements(node_selector)
        if not reqs:
            errors.append(
                f"{path}[{i}] ({name}): nodeSelector must not be empty"
            )
            continue
        parsed.append((name, reqs))
        # per-profile strategy values obey the same ranges
        _check_ranges(prof, _RANGES.get(key, ()), f"{path}[{i}]", errors)
        _check_orderings(prof, _ORDERINGS.get(key, ()), f"{path}[{i}]", errors)
    for i in range(len(parsed)):
        for j in range(i + 1, len(parsed)):
            if _selectors_overlap(parsed[i][1], parsed[j][1]):
                errors.append(
                    f"{path}: profiles {parsed[i][0]!r} and {parsed[j][0]!r} "
                    "have overlapping node selectors"
                )


def validate_slo_configmap(
    new_data: Mapping[str, str],
    old_data: Optional[Mapping[str, str]] = None,
) -> List[str]:
    """Errors for the changed keys of a slo-controller-config update;
    empty list = admit (``validating_handler.go`` Handle)."""
    errors: List[str] = []
    for key in (
        COLOCATION_CONFIG_KEY,
        RESOURCE_THRESHOLD_CONFIG_KEY,
        RESOURCE_QOS_CONFIG_KEY,
        CPU_BURST_CONFIG_KEY,
        SYSTEM_CONFIG_KEY,
        HOST_APPLICATION_CONFIG_KEY,
        CPU_NORMALIZATION_CONFIG_KEY,
        RESOURCE_AMPLIFICATION_CONFIG_KEY,
    ):
        raw = new_data.get(key, "")
        if not raw:
            continue
        if old_data is not None and old_data.get(key, "") == raw:
            continue  # unchanged keys are not re-validated (CommonChecker)
        try:
            cfg = json.loads(raw)
        except (ValueError, TypeError) as e:
            errors.append(f"{key}: invalid JSON: {e}")
            continue
        if not isinstance(cfg, Mapping):
            errors.append(f"{key}: must be a JSON object")
            continue
        _check_ranges(cfg, _RANGES.get(key, ()), key, errors)
        _check_orderings(cfg, _ORDERINGS.get(key, ()), key, errors)
        if key == RESOURCE_QOS_CONFIG_KEY:
            _check_qos_classes(cfg, key, errors)
            for prof in cfg.get("nodeStrategies") or []:
                if isinstance(prof, Mapping):
                    _check_qos_classes(prof, f"{key}.nodeStrategies", errors)
        _check_profiles(cfg, key, key, errors)
    return errors


def _node_matches(selector: Mapping, labels: Mapping[str, str]) -> bool:
    """Evaluate a nodeSelector (matchLabels + matchExpressions) against a
    concrete node's labels; an empty selector matches nothing here (the
    profile checks already rejected it)."""
    ml = selector.get("matchLabels") or {}
    exprs = [e for e in (selector.get("matchExpressions") or [])
             if isinstance(e, Mapping)]
    if not ml and not exprs:
        return False
    if any(labels.get(k) != v for k, v in ml.items()):
        return False
    for expr in exprs:
        key, op = expr.get("key"), expr.get("operator")
        vals = [str(x) for x in expr.get("values") or []]
        has, val = key in labels, labels.get(key)
        if op == "In" and (not has or val not in vals):
            return False
        if op == "NotIn" and has and val in vals:
            return False
        if op == "Exists" and not has:
            return False
        if op == "DoesNotExist" and has:
            return False
    return True


def node_profile_conflicts(
    new_data: Mapping[str, str], node_labels: Mapping[str, str]
) -> List[str]:
    """ExistNodeConflict (``checker.go:142-160``): for one concrete node,
    more than one profile of a config key matching it is a conflict."""
    errors: List[str] = []
    for key, raw in new_data.items():
        if not raw:
            continue
        try:
            cfg = json.loads(raw)
        except (ValueError, TypeError):
            continue
        if not isinstance(cfg, Mapping):
            continue
        matches = []
        for prof in cfg.get("nodeStrategies") or cfg.get("nodeConfigs") or []:
            if not isinstance(prof, Mapping):
                continue
            if _node_matches(prof.get("nodeSelector") or {}, node_labels):
                matches.append(prof.get("name") or "?")
        if len(matches) > 1:
            errors.append(
                f"{key}: node matches multiple profiles {matches}"
            )
    return errors
