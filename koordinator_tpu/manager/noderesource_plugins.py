"""Node resource plugin chain: annotation/label-level node decorations.

Rebuild of the reference's noderesource plugin framework
(``pkg/slo-controller/noderesource/framework/extender_plugin.go:45-263``)
beyond the batch/mid tensors computed in :mod:`noderesource`:

* **cpunormalization** — per-CPU-model performance ratio written to
  ``node.koordinator.sh/cpu-normalization-ratio``
  (``plugins/cpunormalization/plugin.go:129-263``).
* **resourceamplification** — final amplification ratio from user config ×
  normalization ratio (``plugins/resourceamplification/plugin.go:37-90``).
* **gpudeviceresource / rdmadevicereource** — project the Device inventory
  into node-level extended resources + device labels
  (``plugins/gpudeviceresource/plugin.go``, ``plugins/rdmadevicereource/``).

Each plugin is a pure function ``(node, inputs) -> ResourceItems`` so the
chain stays unit-testable the way the reference's table tests are; the
controller applies items as annotation/label/allocatable updates.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence

from ..api import extension as ext
from ..api.types import Device, Node

#: annotation carrying the CPU basic info reported by koordlet
#: (reference ``apis/extension/node.go`` AnnotationNodeCPUBasicInfo)
ANNOTATION_CPU_BASIC_INFO = f"node.{ext.DOMAIN}/cpu-basic-info"


@dataclasses.dataclass
class ResourceItem:
    """One node mutation produced by a plugin (reference
    ``framework.ResourceItem``): extended resource values and/or
    annotation/label writes."""

    name: str
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    reset: bool = False          # degrade: clear owned keys


@dataclasses.dataclass
class CPUBasicInfo:
    """Parsed koordlet-reported CPU model info (reference
    ``apis/extension/node.go`` CPUBasicInfo)."""

    cpu_model: str = ""
    hyper_thread_enabled: bool = False
    turbo_enabled: bool = False


@dataclasses.dataclass
class CPUNormalizationStrategy:
    """slo-controller-config ``cpuNormalizationStrategy``: per-model ratio
    table keyed like the reference's RatioModel
    (``plugin.go:235-263``: pick the entry matching HT/turbo state)."""

    enable: bool = False
    #: model -> {"base": r, "ht": r, "turbo": r, "ht_turbo": r}
    ratio_model: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )


class CPUNormalizationPlugin:
    """Writes the cpu-normalization-ratio annotation."""

    name = "CPUNormalization"

    def __init__(self, strategy: Optional[CPUNormalizationStrategy] = None):
        self.strategy = strategy or CPUNormalizationStrategy()

    def ratio_for(self, info: CPUBasicInfo) -> float:
        """Reference ``getCPUNormalizationRatioFromModel`` (plugin.go:235-263):
        the (HT, turbo) state selects which calibrated ratio applies; a
        missing entry is an error surfaced as ratio 1.0 + skip."""
        model = self.strategy.ratio_model.get(info.cpu_model)
        if model is None:
            raise KeyError(f"no ratio for CPU {info.cpu_model!r}")
        if info.hyper_thread_enabled and info.turbo_enabled:
            key = "ht_turbo"
        elif info.hyper_thread_enabled:
            key = "ht"
        elif info.turbo_enabled:
            key = "turbo"
        else:
            key = "base"
        if key not in model:
            raise KeyError(f"missing {key} ratio for CPU {info.cpu_model!r}")
        ratio = float(model[key])
        if not (0.0 < ratio <= 10.0):
            raise ValueError(f"cpu normalization ratio {ratio} out of range")
        return ratio

    def calculate(self, node: Node, info: Optional[CPUBasicInfo]) -> ResourceItem:
        if not self.strategy.enable or info is None:
            return ResourceItem(name=self.name, reset=True)
        try:
            ratio = self.ratio_for(info)
        except (KeyError, ValueError):
            return ResourceItem(name=self.name, reset=True)
        return ResourceItem(
            name=self.name,
            annotations={ext.ANNOTATION_NODE_CPU_NORMALIZATION: f"{ratio:.4f}"},
        )


class ResourceAmplificationPlugin:
    """Final amplification = user-configured ratio × normalization ratio
    (reference ``plugins/resourceamplification/plugin.go:37-90``: the auto
    path folds the normalization ratio into the cpu amplification)."""

    name = "ResourceAmplification"

    def __init__(self, user_ratios: Optional[Mapping[str, float]] = None):
        #: resource name -> user amplification ratio (≥ 1.0)
        self.user_ratios = dict(user_ratios or {})

    def calculate(self, node: Node, normalization_ratio: float = 1.0) -> ResourceItem:
        ratios = dict(self.user_ratios)
        # final cpu ratio folds in normalization, but is only published when
        # it amplifies (> 1) — reference plugin.go:107-109 — so a weak CPU
        # model never shrinks allocatable below what kubelet reported.
        cpu_ratio = ratios.get(ext.RES_CPU, 1.0) * normalization_ratio
        if cpu_ratio > 1.0:
            ratios[ext.RES_CPU] = cpu_ratio
        else:
            ratios.pop(ext.RES_CPU, None)
        ratios = {k: v for k, v in ratios.items() if v != 1.0}
        if not ratios:
            return ResourceItem(name=self.name, reset=True)
        enc = ",".join(f"{k}={v:.4f}" for k, v in sorted(ratios.items()))
        return ResourceItem(
            name=self.name,
            annotations={ext.ANNOTATION_NODE_AMPLIFICATION: enc},
        )


LABEL_GPU_MODEL = ext.LABEL_GPU_MODEL
LABEL_GPU_DRIVER = f"node.{ext.DOMAIN}/gpu-driver"


class GPUDeviceResourcePlugin:
    """Device CRD → node extended resources: total gpu-core/gpu-memory and
    whole-GPU count (reference ``plugins/gpudeviceresource/plugin.go``)."""

    name = "GPUDeviceResource"

    def calculate(
        self, node: Node, device: Optional[Device], gpu_model: str = ""
    ) -> ResourceItem:
        gpus = [d for d in (device.devices if device else []) if d.dev_type == "gpu"]
        if not gpus:
            return ResourceItem(name=self.name, reset=True)
        total_core = sum(d.resources.get(ext.RES_GPU_CORE, 100.0) for d in gpus)
        total_mem = sum(d.resources.get(ext.RES_GPU_MEMORY, 0.0) for d in gpus)
        item = ResourceItem(
            name=self.name,
            resources={
                ext.RES_GPU: float(len(gpus)),
                ext.RES_GPU_CORE: total_core,
                ext.RES_GPU_MEMORY: total_mem,
            },
        )
        if gpu_model:
            item.labels[LABEL_GPU_MODEL] = gpu_model
        return item


class RDMADeviceResourcePlugin:
    name = "RDMADeviceResource"

    def calculate(self, node: Node, device: Optional[Device]) -> ResourceItem:
        rdmas = [
            d for d in (device.devices if device else []) if d.dev_type == "rdma"
        ]
        if not rdmas:
            return ResourceItem(name=self.name, reset=True)
        return ResourceItem(
            name=self.name, resources={ext.RES_RDMA: float(len(rdmas))}
        )


class FPGADeviceResourcePlugin:
    name = "FPGADeviceResource"

    def calculate(self, node: Node, device: Optional[Device]) -> ResourceItem:
        fpgas = [
            d for d in (device.devices if device else []) if d.dev_type == "fpga"
        ]
        if not fpgas:
            return ResourceItem(name=self.name, reset=True)
        return ResourceItem(
            name=self.name, resources={ext.RES_FPGA: float(len(fpgas))}
        )


#: keys each plugin owns, cleared on reset (the reference's Reset() path
#: returns zeroed ResourceItems for exactly these keys)
_OWNED_ANNOTATIONS = {
    "CPUNormalization": (ext.ANNOTATION_NODE_CPU_NORMALIZATION,),
    "ResourceAmplification": (ext.ANNOTATION_NODE_AMPLIFICATION,),
}
_OWNED_RESOURCES = {
    "GPUDeviceResource": (ext.RES_GPU, ext.RES_GPU_CORE, ext.RES_GPU_MEMORY),
    "RDMADeviceResource": (ext.RES_RDMA,),
    "FPGADeviceResource": (ext.RES_FPGA,),
}
_OWNED_LABELS = {
    "GPUDeviceResource": (LABEL_GPU_MODEL, LABEL_GPU_DRIVER),
}


def apply_items(node: Node, items: Sequence[ResourceItem]) -> Node:
    """Apply plugin outputs to the node object (the reference's
    ``updateNodeResource`` merge: reset clears owned keys, otherwise
    annotations/labels/allocatable merge in)."""
    for item in items:
        if item.reset:
            for key in _OWNED_ANNOTATIONS.get(item.name, ()):
                node.meta.annotations.pop(key, None)
            for key in _OWNED_RESOURCES.get(item.name, ()):
                node.status.allocatable.pop(key, None)
            for key in _OWNED_LABELS.get(item.name, ()):
                node.meta.labels.pop(key, None)
            continue
        node.meta.annotations.update(item.annotations)
        node.meta.labels.update(item.labels)
        node.status.allocatable.update(item.resources)
    return node


def parse_amplification(node: Node) -> Dict[str, float]:
    """Scheduler-side accessor for the amplification annotation (reference
    ``apis/extension/node_resource_amplification.go``)."""
    return dict(ext.parse_node_amplification(node.meta.annotations))
