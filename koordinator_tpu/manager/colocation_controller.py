"""ClusterColocationProfile reconciler for existing pods.

Rebuild of ``pkg/controller/colocationprofile/``: the mutating webhook only
touches pods at admission; when a profile is created or changed, this
controller walks already-admitted pods and applies the profile's mutations
to those that match and are not yet consistent (the reference patches
labels/annotations; scheduler-visible spec fields stay immutable on bound
pods, so only pending pods get resource rewrites).
"""

from __future__ import annotations

from typing import Iterable, List

from ..api.types import ClusterColocationProfile, Pod, PodPhase
from .profile import ProfileMutator


class ColocationProfileController:
    def __init__(self, mutator: ProfileMutator, reconcile_by_default: bool = True):
        self.mutator = mutator
        #: the reference's ReconcileByDefault flag
        #: (``colocationprofile_controller.go:86-91``): when off, only
        #: profiles labeled controller-managed="true" are reconciled
        self.reconcile_by_default = reconcile_by_default

    def _enabled(self, profile) -> bool:
        from ..api import extension as ext

        return self.reconcile_by_default or ext.should_reconcile_profile(
            profile.meta
        )

    def reconcile(self, pods: Iterable[Pod]) -> List[Pod]:
        """Returns the pods that were changed."""
        changed: List[Pod] = []
        for pod in pods:
            matched = [
                p for p in self.mutator.match(pod) if self._enabled(p)
            ]
            if not matched:
                continue
            before = (
                dict(pod.meta.labels),
                dict(pod.meta.annotations),
                pod.spec.priority,
                pod.spec.scheduler_name,
                dict(pod.spec.requests),
                dict(pod.spec.limits),
            )
            if pod.phase is PodPhase.PENDING and pod.spec.node_name is None:
                self.mutator.mutate_with(pod, matched)
            else:
                # bound pods: metadata-only reconcile
                for p in sorted(matched, key=lambda p: p.meta.name):
                    pod.meta.labels.update(p.labels)
                    pod.meta.annotations.update(p.annotations)
            after = (
                dict(pod.meta.labels),
                dict(pod.meta.annotations),
                pod.spec.priority,
                pod.spec.scheduler_name,
                dict(pod.spec.requests),
                dict(pod.spec.limits),
            )
            if before != after:
                changed.append(pod)
        return changed
