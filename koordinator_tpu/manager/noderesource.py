"""Node resource controller: batch/mid overcommit calculation.

Rebuild of ``pkg/slo-controller/noderesource/`` (framework
``extender_plugin.go:45-263``, ``plugins/batchresource/plugin.go:169``):
from koordlet-reported NodeMetrics, compute per-node colocatable capacity

    batch = allocatable × (1 − reserve) − prodPeak − sysUsage
    mid   = prodReclaimable = max(prodAllocatable − prodPeak, 0) × ratio

and publish it as the ``kubernetes.io/batch-*`` / ``mid-*`` extended
resources. Unlike the reference's per-node reconcile loop, the whole
calculation is one vectorized pass over the snapshot's node axis — the
updated batch dims feed straight back into the scheduler's allocatable
tensor, closing the colocation loop of SURVEY §3.3.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional, Tuple

import numpy as np

from ..api import extension as ext
from ..core.snapshot import ClusterSnapshot


@dataclasses.dataclass
class ColocationStrategy:
    """slo-controller-config colocation knobs (reference
    ``apis/configuration/slo_controller_config.go`` ColocationStrategy)."""

    enable: bool = True
    #: fraction of allocatable reserved from colocation (degradation buffer)
    reserve_ratio: float = 0.1
    #: prod peak = max(usage, requests × this safety factor)
    prod_request_factor: float = 0.0  # 0 = usage-only (usage policy)
    #: fraction of reclaimable prod capacity exposed as mid-tier
    mid_reclaim_ratio: float = 0.5
    #: degrade (zero batch resources) when NodeMetric is stale
    degrade_on_stale_metric: bool = True


class NodeResourceController:
    """Computes batch/mid extended resources over the node axis."""

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        strategy: Optional[ColocationStrategy] = None,
    ):
        self.snapshot = snapshot
        self.strategy = strategy or ColocationStrategy()
        cfg = snapshot.config
        self._cpu = cfg.resources.index(ext.RES_CPU)
        self._mem = cfg.resources.index(ext.RES_MEMORY)
        self._batch = {
            r: cfg.resources.index(r)
            for r in (ext.RES_BATCH_CPU, ext.RES_BATCH_MEMORY)
            if r in cfg.resources
        }
        self._mid = {
            r: cfg.resources.index(r)
            for r in (ext.RES_MID_CPU, ext.RES_MID_MEMORY)
            if r in cfg.resources
        }

    def calculate(self) -> Tuple[np.ndarray, np.ndarray]:
        """(batch [N, 2], mid [N, 2]) in (cpu, memory) units."""
        na = self.snapshot.nodes
        s = self.strategy
        base = na.allocatable[:, [self._cpu, self._mem]]
        # prod peak per the usage policy; the reference additionally
        # subtracts system-tier usage, which koordlet reports separately —
        # here the reserve_ratio buffer covers it (NodeMetric.sys_usage is
        # not folded into the snapshot arrays).
        prod_peak = (
            na.prod_usage[:, [self._cpu, self._mem]]
            + na.assigned_pending_prod[:, [self._cpu, self._mem]]
        )
        if s.prod_request_factor > 0:
            prod_req = na.requested[:, [self._cpu, self._mem]]
            prod_peak = np.maximum(prod_peak, prod_req * s.prod_request_factor)
        batch = np.maximum(base * (1.0 - s.reserve_ratio) - prod_peak, 0.0)
        # mid = reclaimable prod capacity: what prod-tier pods requested but
        # do not actually use at peak (reference midresource plugin) — NOT
        # total allocatable headroom, which would overstate mid capacity.
        prod_requested = na.requested[:, [self._cpu, self._mem]]
        mid = np.maximum(prod_requested - prod_peak, 0.0) * s.mid_reclaim_ratio
        if not s.enable:
            batch = np.zeros_like(batch)
            mid = np.zeros_like(mid)
        if s.degrade_on_stale_metric:
            stale = ~na.metric_fresh
            batch[stale] = 0.0
            mid[stale] = 0.0
        return batch.astype(np.float32), mid.astype(np.float32)

    def reconcile(self) -> Dict[str, Dict[str, float]]:
        """Write batch/mid columns back into the snapshot's allocatable
        tensor (the reference writes Node.status.allocatable, which the
        scheduler sees via its informer — here it is the same array).
        Returns {node: {resource: value}} for status publication."""
        batch, mid = self.calculate()
        na = self.snapshot.nodes
        updates: Dict[str, Dict[str, float]] = {}
        for res, col in self._batch.items():
            na.allocatable[:, col] = batch[:, 0 if "cpu" in res else 1]
        for res, col in self._mid.items():
            na.allocatable[:, col] = mid[:, 0 if "cpu" in res else 1]
        for name, idx in list(self.snapshot._node_index.items()):
            row: Dict[str, float] = {}
            for res, col in self._batch.items():
                row[res] = float(na.allocatable[idx, col])
            for res, col in self._mid.items():
                row[res] = float(na.allocatable[idx, col])
            updates[name] = row
        return updates
