"""Node resource controller: batch/mid overcommit calculation.

Rebuild of ``pkg/slo-controller/noderesource/`` (framework
``extender_plugin.go:45-263``, ``plugins/batchresource/plugin.go:169``):
from koordlet-reported NodeMetrics, compute per-node colocatable capacity

    batch = allocatable × (1 − reserve) − prodPeak − sysUsage
    mid   = prodReclaimable = max(prodAllocatable − prodPeak, 0) × ratio

and publish it as the ``kubernetes.io/batch-*`` / ``mid-*`` extended
resources. Unlike the reference's per-node reconcile loop, the whole
calculation is one vectorized pass over the snapshot's node axis — the
updated batch dims feed straight back into the scheduler's allocatable
tensor, closing the colocation loop of SURVEY §3.3.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional, Tuple

import numpy as np

from ..api import extension as ext
from ..core.snapshot import ClusterSnapshot


@dataclasses.dataclass
class ColocationStrategy:
    """slo-controller-config colocation knobs (reference
    ``apis/configuration/slo_controller_config.go`` ColocationStrategy)."""

    enable: bool = True
    #: fraction of allocatable reserved from colocation (degradation buffer;
    #: the reference's nodeSafetyMargin percent)
    reserve_ratio: float = 0.1
    #: prod peak = max(usage, requests × this safety factor)
    prod_request_factor: float = 0.0  # 0 = usage-only (usage policy)
    #: fraction of reclaimable prod capacity exposed as mid-tier
    mid_reclaim_ratio: float = 0.5
    #: degrade (zero batch resources) when NodeMetric is stale
    degrade_on_stale_metric: bool = True
    #: node-reserved floor (max of kubelet/annotation reserved in the
    #: reference; subtracted as max(systemUsed, reserved))
    node_reserved: Mapping[str, float] = dataclasses.field(default_factory=dict)
    #: batch cpu policy: "usage" (default) | "maxUsageRequest"
    #: (CalculateBatchResourceByPolicy, plugins/util/util.go:50-105)
    cpu_calculate_policy: str = "usage"
    #: batch memory policy: "usage" (default) | "request" | "maxUsageRequest"
    memory_calculate_policy: str = "usage"


class NodeResourceController:
    """Computes batch/mid extended resources over the node axis."""

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        strategy: Optional[ColocationStrategy] = None,
    ):
        self.snapshot = snapshot
        self.strategy = strategy or ColocationStrategy()
        cfg = snapshot.config
        self._cpu = cfg.resources.index(ext.RES_CPU)
        self._mem = cfg.resources.index(ext.RES_MEMORY)
        self._batch = {
            r: cfg.resources.index(r)
            for r in (ext.RES_BATCH_CPU, ext.RES_BATCH_MEMORY)
            if r in cfg.resources
        }
        self._mid = {
            r: cfg.resources.index(r)
            for r in (ext.RES_MID_CPU, ext.RES_MID_MEMORY)
            if r in cfg.resources
        }

    def calculate(self) -> Tuple[np.ndarray, np.ndarray]:
        """(batch [N, 2], mid [N, 2]) in (cpu, memory) units.

        Reference formula (``CalculateBatchResourceByPolicy``,
        ``plugins/util/util.go:50-105``), per policy:

          usage:           cap − margin − max(sysUsed, reserved) − prodUsed
          request:         cap − margin − reserved − prodRequested
          maxUsageRequest: cap − margin − max(sysUsed, reserved)
                               − max(prodUsed, prodRequested)

        clamped ≥ 0; cpu selects usage|maxUsageRequest, memory any of the
        three (the reference's per-resource CalculatePolicy knobs).
        """
        na = self.snapshot.nodes
        s = self.strategy
        cols = [self._cpu, self._mem]
        base = na.allocatable[:, cols]
        # per-node overrides (node_colocation.go), parsed once at
        # upsert_node into dense columns: reclaim ratio r keeps
        # r×allocatable for colocation (margin = (1−r)×allocatable), and
        # colo_enable is a tri-state that takes precedence over the
        # cluster enable in BOTH directions
        reclaim = np.where(
            na.colo_reclaim > 0.0, na.colo_reclaim, 1.0 - s.reserve_ratio
        )
        margin = base * (1.0 - reclaim)
        reserved = self.snapshot.config.res_vector(s.node_reserved)[cols]
        sys_used = np.maximum(na.sys_usage[:, cols], reserved[None, :])
        prod_used = (
            na.prod_usage[:, cols] + na.assigned_pending_prod[:, cols]
        )
        if s.prod_request_factor > 0:
            prod_req_f = na.requested[:, cols] * s.prod_request_factor
            prod_used = np.maximum(prod_used, prod_req_f)
        prod_requested = na.requested[:, cols]

        by_usage = np.maximum(base - margin - sys_used - prod_used, 0.0)
        by_request = np.maximum(
            base - margin - reserved[None, :] - prod_requested, 0.0
        )
        by_max = np.maximum(
            base - margin - sys_used - np.maximum(prod_used, prod_requested),
            0.0,
        )
        policies = {
            "usage": by_usage,
            "request": by_request,
            "maxUsageRequest": by_max,
        }
        batch = by_usage.copy()
        batch[:, 0] = policies.get(s.cpu_calculate_policy, by_usage)[:, 0]
        batch[:, 1] = policies.get(s.memory_calculate_policy, by_usage)[:, 1]
        # mid = reclaimable prod capacity: what prod-tier pods requested but
        # do not actually use at peak (reference midresource plugin) — NOT
        # total allocatable headroom, which would overstate mid capacity.
        mid = np.maximum(prod_requested - prod_used, 0.0) * s.mid_reclaim_ratio
        enable_eff = np.where(
            na.colo_enable >= 0, na.colo_enable.astype(bool), s.enable
        )
        batch[~enable_eff] = 0.0
        mid[~enable_eff] = 0.0
        if s.degrade_on_stale_metric:
            stale = ~na.metric_fresh
            batch[stale] = 0.0
            mid[stale] = 0.0
        return batch.astype(np.float32), mid.astype(np.float32)

    def reconcile(self) -> Dict[str, Dict[str, float]]:
        """Write batch/mid columns back into the snapshot's allocatable
        tensor (the reference writes Node.status.allocatable, which the
        scheduler sees via its informer — here it is the same array).
        Returns {node: {resource: value}} for status publication."""
        batch, mid = self.calculate()
        na = self.snapshot.nodes
        updates: Dict[str, Dict[str, float]] = {}
        cols = list(self._batch.values()) + list(self._mid.values())
        before = na.allocatable[:, cols].copy() if cols else None
        for res, col in self._batch.items():
            na.allocatable[:, col] = batch[:, 0 if "cpu" in res else 1]
        for res, col in self._mid.items():
            na.allocatable[:, col] = mid[:, 0 if "cpu" in res else 1]
        if before is not None:
            # mark only the rows the rewrite actually moved — a steady
            # reconcile must not wipe the device-resident NodeState's
            # dirty-row scatter path with a blanket invalidation
            changed = np.nonzero(
                (na.allocatable[:, cols] != before).any(axis=1)
            )[0]
            if len(changed):
                self.snapshot.touch_rows(changed)
        for name, idx in list(self.snapshot._node_index.items()):
            row: Dict[str, float] = {}
            for res, col in self._batch.items():
                row[res] = float(na.allocatable[idx, col])
            for res, col in self._mid.items():
                row[res] = float(na.allocatable[idx, col])
            updates[name] = row
        return updates
