"""Node + config validating webhooks.

Rebuild of ``pkg/webhook/node/`` (resource-amplification annotation
validation) and ``pkg/webhook/cm/`` (slo-controller-config ConfigMap
validation): reject malformed dynamic config before controllers render it.
"""

from __future__ import annotations

from typing import List, Mapping

from ..api import extension as ext
from ..api.types import Node, ResourceThresholdStrategy
from .noderesource import ColocationStrategy
from .noderesource_plugins import parse_amplification


def mutate_node_status(node: Node) -> Node:
    """Apply resource-amplification ratios to the node's allocatable
    (reference ``pkg/webhook/node/mutating``): the RAW allocatable is
    preserved in the raw-allocatable annotation (idempotent across
    repeated status updates — ratios always apply to the raw base, never
    compound), and the amplified values land in status.allocatable where
    the scheduler's informer — here the snapshot — picks them up."""
    import json

    ratios = parse_amplification(node)
    if not ratios:
        return node
    raw_s = node.meta.annotations.get(ext.ANNOTATION_NODE_RAW_ALLOCATABLE)
    if raw_s:
        try:
            raw = {k: float(v) for k, v in json.loads(raw_s).items()}
        except (ValueError, TypeError):
            raw = dict(node.status.allocatable)
    else:
        raw = dict(node.status.allocatable)
        node.meta.annotations[ext.ANNOTATION_NODE_RAW_ALLOCATABLE] = json.dumps(
            raw
        )
    for res, ratio in ratios.items():
        if res in raw and ratio >= 1.0:
            node.status.allocatable[res] = raw[res] * ratio
    return node


def validate_node(node: Node) -> List[str]:
    """Amplification ratios must parse and be ≥ 1.0 (reference
    ``pkg/webhook/node/validating``)."""
    errors: List[str] = []
    raw = node.meta.annotations.get(ext.ANNOTATION_NODE_AMPLIFICATION)
    if raw is None:
        return errors
    ratios = parse_amplification(node)
    parts = [p for p in raw.split(",") if p]
    if len(ratios) != len(parts):
        errors.append(f"node {node.meta.name}: malformed amplification {raw!r}")
    for key, val in ratios.items():
        if val < 1.0:
            errors.append(
                f"node {node.meta.name}: amplification ratio {key}={val} < 1.0"
            )
    return errors


def validate_colocation_strategy(strategy: ColocationStrategy) -> List[str]:
    """slo-controller-config colocation sanity (reference
    ``pkg/webhook/cm/`` plugin ``configmap_validate.go`` semantics)."""
    errors: List[str] = []
    if not 0.0 <= strategy.reserve_ratio < 1.0:
        errors.append(f"reserveRatio {strategy.reserve_ratio} outside [0, 1)")
    if strategy.prod_request_factor < 0.0:
        errors.append("prodRequestFactor < 0")
    if not 0.0 <= strategy.mid_reclaim_ratio <= 1.0:
        errors.append(f"midReclaimRatio {strategy.mid_reclaim_ratio} outside [0, 1]")
    return errors


def validate_threshold_strategy(s: ResourceThresholdStrategy) -> List[str]:
    errors: List[str] = []
    for name in (
        "cpu_suppress_threshold_percent",
        "cpu_evict_be_usage_threshold_percent",
        "memory_evict_threshold_percent",
    ):
        val = getattr(s, name)
        if not 0.0 <= val <= 100.0:
            errors.append(f"{name}={val} outside [0, 100]")
    low = s.memory_evict_lower_percent
    if low is not None and low >= s.memory_evict_threshold_percent:
        errors.append(
            "memoryEvictLowerPercent must be below memoryEvictThresholdPercent"
        )
    return errors
