"""Node + config validating webhooks.

Rebuild of ``pkg/webhook/node/`` (resource-amplification annotation
validation) and ``pkg/webhook/cm/`` (slo-controller-config ConfigMap
validation): reject malformed dynamic config before controllers render it.
"""

from __future__ import annotations

from typing import List, Mapping

from ..api import extension as ext
from ..api.types import Node, ResourceThresholdStrategy
from .noderesource import ColocationStrategy
from .noderesource_plugins import parse_amplification


def validate_node(node: Node) -> List[str]:
    """Amplification ratios must parse and be ≥ 1.0 (reference
    ``pkg/webhook/node/validating``)."""
    errors: List[str] = []
    raw = node.meta.annotations.get(ext.ANNOTATION_NODE_AMPLIFICATION)
    if raw is None:
        return errors
    ratios = parse_amplification(node)
    parts = [p for p in raw.split(",") if p]
    if len(ratios) != len(parts):
        errors.append(f"node {node.meta.name}: malformed amplification {raw!r}")
    for key, val in ratios.items():
        if val < 1.0:
            errors.append(
                f"node {node.meta.name}: amplification ratio {key}={val} < 1.0"
            )
    return errors


def validate_colocation_strategy(strategy: ColocationStrategy) -> List[str]:
    """slo-controller-config colocation sanity (reference
    ``pkg/webhook/cm/`` plugin ``configmap_validate.go`` semantics)."""
    errors: List[str] = []
    if not 0.0 <= strategy.reserve_ratio < 1.0:
        errors.append(f"reserveRatio {strategy.reserve_ratio} outside [0, 1)")
    if strategy.prod_request_factor < 0.0:
        errors.append("prodRequestFactor < 0")
    if not 0.0 <= strategy.mid_reclaim_ratio <= 1.0:
        errors.append(f"midReclaimRatio {strategy.mid_reclaim_ratio} outside [0, 1]")
    return errors


def validate_threshold_strategy(s: ResourceThresholdStrategy) -> List[str]:
    errors: List[str] = []
    for name in (
        "cpu_suppress_threshold_percent",
        "cpu_evict_be_usage_threshold_percent",
        "memory_evict_threshold_percent",
    ):
        val = getattr(s, name)
        if not 0.0 <= val <= 100.0:
            errors.append(f"{name}={val} outside [0, 100]")
    low = s.memory_evict_lower_percent
    if low is not None and low >= s.memory_evict_threshold_percent:
        errors.append(
            "memoryEvictLowerPercent must be below memoryEvictThresholdPercent"
        )
    return errors
