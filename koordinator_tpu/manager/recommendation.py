"""Recommendation controller (``analysis.koordinator.sh``).

The reference ships the CRD types only
(``apis/analysis/v1alpha1/recommendation_types.go`` — SURVEY §2.7 calls it
"largely scaffolding"); the natural owner of the data is the prediction
subsystem, so here the controller is wired end-to-end: per-workload usage
samples feed the same decayed-histogram PeakPredictor the koordlet uses
(``pkg/koordlet/prediction``), and reconcile emits a Recommendation whose
resources are the p95 peak with a safety margin — the shape the reference's
RecommendedContainerStatus carries.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Mapping, Optional

from ..api.types import ObjectMeta, Recommendation
from ..koordlet.prediction import PeakPredictor, PredictorConfig


def _subject(workload: str, resource: str) -> str:
    return f"{workload}#{resource}"


class RecommendationController:
    """Aggregates workload usage into p95-peak resource recommendations."""

    def __init__(
        self,
        predictor: Optional[PeakPredictor] = None,
        percentile: float = 95.0,
        safety_margin: float = 1.15,
    ):
        # margin is applied once, here — the embedded predictor's own
        # safety factor is disabled so the two don't compound
        self.predictor = predictor or PeakPredictor(
            PredictorConfig(safety_margin=1.0)
        )
        self.percentile = percentile
        self.safety_margin = safety_margin
        self._workloads: Dict[str, set] = {}
        self.recommendations: Dict[str, Recommendation] = {}

    def observe(
        self, workload: str, usage: Mapping[str, float], ts: Optional[float] = None
    ) -> None:
        """One usage sample for a workload (sum over its pods)."""
        ts = ts if ts is not None else time.time()
        resources = self._workloads.setdefault(workload, set())
        for res, value in usage.items():
            resources.add(res)
            self.predictor.observe(_subject(workload, res), float(value), ts)

    def recommend(self, workload: str) -> Optional[Recommendation]:
        resources = self._workloads.get(workload)
        if not resources:
            return None
        recommended: Dict[str, float] = {}
        for res in sorted(resources):
            peak = self.predictor.peak(_subject(workload, res), self.percentile)
            if peak is not None:
                recommended[res] = peak * self.safety_margin
        if not recommended:
            return None
        return Recommendation(
            meta=ObjectMeta(name=workload),
            workload_name=workload,
            recommended=recommended,
        )

    def reconcile(
        self, workloads: Optional[Iterable[str]] = None
    ) -> Dict[str, Recommendation]:
        """Refresh Recommendation objects (all known workloads by default);
        drops recommendations whose workload disappeared."""
        names = set(workloads) if workloads is not None else set(self._workloads)
        for name in list(self.recommendations):
            if name not in names:
                del self.recommendations[name]
        # GC sample state too, or the next argument-less reconcile would
        # resurrect the workload from stale histograms
        for name in list(self._workloads):
            if name not in names:
                for res in self._workloads.pop(name):
                    self.predictor.forget(_subject(name, res))
        for name in names:
            rec = self.recommend(name)
            if rec is not None:
                self.recommendations[name] = rec
        return dict(self.recommendations)
