"""ClusterColocationProfile admission mutation.

Rebuild of the reference webhook
(``pkg/webhook/pod/mutating/cluster_colocation_profile.go`` +
``apis/config/v1alpha1/cluster_colocation_profile_types.go``): pods matching
a profile's label/namespace selectors get labels, annotations, QoS,
priority, scheduler name, and resource-name rewrites (e.g. cpu →
``kubernetes.io/batch-cpu``) injected at admission — this is how Spark
executor pods become BE/batch-tier without the submitter changing anything
(reference ``examples/spark-jobs/cluster-colocation-profile.yaml``).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence

from ..api import extension as ext
from ..api.types import ClusterColocationProfile, Pod
from .validating import validate_pod


def _selector_matches(selector: Mapping[str, str], labels: Mapping[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class ProfileMutator:
    """Admission-time pod mutation (+ validation) pipeline."""

    def __init__(
        self,
        profiles: Optional[Sequence[ClusterColocationProfile]] = None,
        namespace_labels: Optional[Mapping[str, Mapping[str, str]]] = None,
    ):
        self.profiles: List[ClusterColocationProfile] = list(profiles or [])
        #: namespace -> labels, for namespaceSelector matching
        self.namespace_labels = dict(namespace_labels or {})

    def upsert(self, profile: ClusterColocationProfile) -> None:
        self.profiles = [
            p for p in self.profiles if p.meta.name != profile.meta.name
        ] + [profile]

    def match(self, pod: Pod) -> List[ClusterColocationProfile]:
        out = []
        for p in self.profiles:
            if p.selector and not _selector_matches(p.selector, pod.meta.labels):
                continue
            if p.namespace_selector:
                ns_labels = self.namespace_labels.get(pod.meta.namespace, {})
                if not _selector_matches(p.namespace_selector, ns_labels):
                    continue
            out.append(p)
        return out

    @staticmethod
    def _apply(
        p: ClusterColocationProfile,
        meta,
        resource_stores,
        skip_resources: bool = False,
    ) -> None:
        """One profile's mutation against any object's (meta,
        resource dicts) — the single source of truth for both the pod and
        the reservation webhook paths. ``skip_resources`` suppresses the
        resource-name rewrite (the skip-update-resources annotation,
        ``cluster_colocation_profile.go:94-115``: labels/QoS/priority
        still apply; only the resource spec mutation is skipped)."""
        meta.labels.update(p.labels)
        meta.annotations.update(p.annotations)
        if p.qos_class is not None:
            meta.labels[ext.LABEL_POD_QOS] = p.qos_class.name
        if p.resource_translation and not skip_resources:
            for store in resource_stores:
                for src, dst in p.resource_translation.items():
                    if src in store:
                        store[dst] = store.pop(src)

    def mutate(self, pod: Pod) -> Pod:
        """Apply all matching profiles in name order (deterministic)."""
        return self.mutate_with(pod, self.match(pod))

    def mutate_with(self, pod: Pod, profiles) -> Pod:
        """Apply the given (already-matched) profiles in name order.
        ANY profile carrying the skip-update-resources annotation
        suppresses the resource mutation for the whole pod (the webhook
        accumulates the flag across profiles before mutating the
        resource spec, ``cluster_colocation_profile.go:94-97,113-115``)."""
        matched = sorted(profiles, key=lambda p: p.meta.name)
        skip_resources = any(
            ext.should_skip_update_resource(p.meta) for p in matched
        )
        for p in matched:
            self._apply(
                p,
                pod.meta,
                (pod.spec.requests, pod.spec.limits),
                skip_resources=skip_resources,
            )
            if p.priority is not None:
                pod.spec.priority = p.priority
            if p.scheduler_name is not None:
                pod.spec.scheduler_name = p.scheduler_name
        return pod

    def admit(self, pod: Pod) -> List[str]:
        """Mutate then validate; returns validation errors (empty = admitted)."""
        self.mutate(pod)
        return validate_pod(pod)

    def mutate_reservation(self, reservation) -> None:
        """Reservation-create mutation (reference
        ``pkg/webhook/reservation/mutating/cluster_colocation_profile.go``):
        matching profiles rewrite the reservation's labels/annotations,
        QoS label, and resource names the same way they rewrite pods, so a
        reservation created for profile-managed workloads holds capacity
        in the *translated* resource dims (e.g. batch-cpu). Reservations
        do not support the namespaceSelector (reference comment)."""
        matched = [
            p
            for p in self.profiles
            if not p.selector
            or _selector_matches(p.selector, reservation.meta.labels)
        ]
        for p in sorted(matched, key=lambda p: p.meta.name):
            self._apply(p, reservation.meta, (reservation.requests,))
