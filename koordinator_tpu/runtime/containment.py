"""Gray-failure containment: poison-batch quarantine, crash-loop
governor, informer staleness watchdog (gray-failure containment PR).

Every robustness layer so far defends against components that *die* —
crashes (PR 5/6), drops (PR 3), torn records (PR 14). A production
control plane is more often taken down by things that are *wrong but
alive*:

* a **poison pod** whose lowering deterministically raises crashes the
  leader, is faithfully resubmitted by journal replay, and crashes every
  successor — a fleet-wide crash-loop born from ONE bad spec;
* a **crash-looping incarnation** burns boot after boot at full speed,
  each takeover re-paying recovery before dying again;
* a **connected-but-silent informer** stops delivering events while its
  watch stays open — every controller keeps acting on stale evidence
  with ``/healthz`` green.

This module holds the three containment mechanisms; the wiring lives in
``scheduler.batch_solver`` (bisection + cycle gate + stale-evidence
preemption refusal), ``runtime.ha`` (blame adoption BEFORE replay, boot
backoff), ``runtime.statehub``/``utils.informer`` (freshness plumbing)
and ``sim.longrun`` (the soak arm).

Design rules carried over from earlier PRs:

* both ledgers ride the PR 14 journal-store codec (they WRAP a
  ``MemoryJournalStore``/``FileJournalStore`` — sealed records, screened
  loads, ``journal_fsck``-able) instead of inventing a second format;
* the crash-loop decision is snapshot-once → pure :meth:`decide` →
  ``DecisionLedger.record`` (PR 15 contract, controller ``crashloop``);
* the watchdog takes an injectable clock and is driven from the caller's
  thread — soak arms stay deterministic (ROADMAP chaos rule).
"""

from __future__ import annotations

import dataclasses
import threading
import time as _time
from typing import Dict, List, Optional

from ..core import integrity
from ..core.journal import MemoryJournalStore
from ..obs.errors import report_exception

#: pods carrying this label are the chaos layer's poison carriers: the
#: ``solver.poison_batch`` point only raises while lowering a batch that
#: contains one (a label-blind fire would poison EVERY probe and the
#: bisection could never terminate). Real poison needs no label — any
#: deterministic lowering exception takes the same containment path.
POISON_LABEL = "koordinator.sh/poison-batch"


class PoisonBatchError(RuntimeError):
    """A batch lowering failed deterministically (poison spec or the
    injected ``solver.poison_batch`` fault)."""


class BootCrashError(RuntimeError):
    """A takeover died mid-boot (the ``scheduler.boot_crash`` fault or a
    real recovery crash) — caught by the coordinator's tick so the
    incarnation steps back to standby instead of killing the process."""


def _kv(d) -> object:
    """Canonical JSON-shaped view of a possibly-None mapping."""
    if isinstance(d, dict):
        return sorted((str(k), v) for k, v in d.items())
    return d


def spec_fingerprint(pod) -> str:
    """Restart-stable content digest of everything that makes a pod's
    *spec* (the quarantine redemption key). ``core.snapshot.
    pod_fingerprint`` is NOT usable here: it folds Python ``hash()``
    (PYTHONHASHSEED-randomized) — fine for an in-process row cache,
    useless for a ledger a successor incarnation must honor."""
    spec, meta = pod.spec, pod.meta
    return integrity.payload_digest(
        {
            "priority": spec.priority,
            "requests": _kv(spec.requests),
            "limits": _kv(spec.limits),
            "estimated": _kv(getattr(spec, "estimated", None)),
            "labels": _kv(meta.labels),
            "annotations": _kv(meta.annotations),
        }
    )


class QuarantineLedger:
    """Sealed blame ledger for poison pods, beside the shard journal.

    Records ride the journal-store codec: ``blame`` records carry
    ``{uid, fp, evidence, incarnation, cseq, cycle}``; a ``redeem``
    record lifts the blame (written when the pod reappears with a
    CHANGED spec fingerprint — the redeemable-ticket contract: fixing
    the spec is what re-admits, resubmitting the same bytes is not).

    The ledger lives beside the shard journal precisely so a takeover
    adopts blame BEFORE replaying the queue (``runtime.ha``): the
    predecessor's killer is rejected at the successor's cycle gate
    instead of crashing the successor too.
    """

    def __init__(self, store=None, incarnation: str = "", registry=None):
        self.store = store if store is not None else MemoryJournalStore(
            name="quarantine"
        )
        self.incarnation = incarnation
        self.registry = registry
        self._lock = threading.Lock()
        #: uid -> active blame record (blame minus redeem, replay order)
        self._blamed: Dict[str, dict] = {}
        self._seq = 0
        self._cseq = 0
        self._adopt_locked()

    # ---- load/adopt ----

    def _adopt_locked(self) -> None:
        try:
            records = self.store.load()
        except Exception as exc:  # noqa: BLE001 — ledger is best-effort
            report_exception(
                "containment.quarantine.load", exc, registry=self.registry
            )
            records = []
        blamed: Dict[str, dict] = {}
        for r in records:
            op = r.get("op")
            if op == "blame":
                blamed[r.get("uid", "")] = dict(r)
            elif op == "redeem":
                blamed.pop(r.get("uid", ""), None)
            if isinstance(r.get("seq"), int):
                self._seq = max(self._seq, r["seq"])
            if isinstance(r.get("cseq"), int):
                self._cseq = max(self._cseq, r["cseq"])
        self._blamed = blamed

    def adopt(self, incarnation: Optional[str] = None) -> int:
        """Takeover path: reload blame from the store (the predecessor's
        appends) and stamp this incarnation onto future records. Returns
        the number of active blames adopted — the successor's cycle gate
        is armed from this moment, BEFORE any queue replay."""
        with self._lock:
            if incarnation is not None:
                self.incarnation = incarnation
            self._adopt_locked()
            return len(self._blamed)

    # ---- write side ----

    def _append_locked(self, record: dict) -> None:
        self._seq += 1
        self._cseq += 1
        record["seq"] = self._seq
        record["cseq"] = self._cseq
        record["incarnation"] = self.incarnation
        try:
            self.store.append(record)
        except Exception as exc:  # noqa: BLE001 — blame must not crash
            report_exception(
                "containment.quarantine.append",
                exc,
                registry=self.registry,
            )

    def blame(
        self, uid: str, fp: str, evidence: str, cycle: int = -1
    ) -> bool:
        """Record blame for ``uid`` at spec fingerprint ``fp``.
        Idempotent per (uid, fp): the bisection re-isolating an
        already-blamed pod (replayed queue on a successor that adopted
        late) appends nothing. Returns True when a NEW blame landed."""
        with self._lock:
            prev = self._blamed.get(uid)
            if prev is not None and prev.get("fp") == fp:
                return False
            rec = {
                "op": "blame",
                "uid": uid,
                "fp": fp,
                "evidence": str(evidence)[:512],
                "cycle": int(cycle),
            }
            self._append_locked(rec)
            self._blamed[uid] = dict(rec)
            return True

    def blamed(self, uid: str, fp: str) -> bool:
        """Cycle-gate check: is ``uid`` quarantined at THIS fingerprint?
        A changed fingerprint is the redeemable ticket — the blame is
        lifted (a ``redeem`` record journals the decision) and the pod
        re-admits through the ordinary path."""
        with self._lock:
            rec = self._blamed.get(uid)
            if rec is None:
                return False
            if rec.get("fp") == fp:
                return True
            self._append_locked(
                {"op": "redeem", "uid": uid, "fp": fp, "cycle": -1}
            )
            self._blamed.pop(uid, None)
            return False

    # ---- read side ----

    def active(self) -> bool:
        """Cheap gate arm: any blame outstanding?"""
        return bool(self._blamed)

    def entries(self) -> Dict[str, dict]:
        """uid -> active blame record (copies; soak asserts read this)."""
        with self._lock:
            return {u: dict(r) for u, r in self._blamed.items()}


@dataclasses.dataclass
class BootPlan:
    """What the crash-loop governor decided a boot should look like."""

    degraded: bool = False
    backoff_s: float = 0.0
    rapid_deaths: int = 0
    #: DEGRADED boot knobs (only meaningful when ``degraded``): the
    #: brownout ladder is pinned at least this high, the pipeline runs
    #: depth 1 (serial), and the solver boots at the host-reference
    #: ladder floor with bisection armed from cycle one — a poison
    #: replay is then contained on the FIRST cycle instead of after
    #: another death.
    brownout_cap: int = 0
    pipeline_depth: int = 0
    bisect_armed: bool = False


class CrashLoopGovernor:
    """Incarnation boot/death ledger + exponential boot backoff.

    ``note_boot``/``note_death`` append sealed records to the crash
    ledger (same codec as the quarantine ledger). Each death runs the
    PR 15 decision contract — :meth:`snapshot` once, pure static
    :meth:`decide`, ``DecisionLedger.record("crashloop", ...)`` — and
    the resulting :class:`BootPlan` gates re-contention
    (:meth:`may_boot`) and shapes the next takeover (DEGRADED boot).
    """

    def __init__(
        self,
        store=None,
        k: int = 3,
        horizon_s: float = 30.0,
        base_backoff_s: float = 0.5,
        max_backoff_s: float = 8.0,
        clock=None,
        decisions=None,
        registry=None,
        incarnation: str = "",
        degraded_brownout_cap: int = 2,
    ):
        self.store = store if store is not None else MemoryJournalStore(
            name="crashloop"
        )
        self.k = max(1, int(k))
        self.horizon_s = float(horizon_s)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.clock = clock or _time.monotonic
        #: obs.decisions.DecisionLedger (None = decisions unrecorded);
        #: spelled ``decisions`` per the decision-ledger lint contract
        self.decisions = decisions
        self.registry = registry
        self.incarnation = incarnation
        self.degraded_brownout_cap = int(degraded_brownout_cap)
        self._lock = threading.Lock()
        self._seq = 0
        self._deaths: List[float] = []
        self._boots = 0
        self._blocked_until = -float("inf")
        self._plan = BootPlan()
        self._load_locked()

    def _load_locked(self) -> None:
        try:
            records = self.store.load()
        except Exception as exc:  # noqa: BLE001 — ledger is best-effort
            report_exception(
                "containment.crashloop.load", exc, registry=self.registry
            )
            records = []
        for r in records:
            if isinstance(r.get("seq"), int):
                self._seq = max(self._seq, r["seq"])
            if r.get("op") == "death":
                self._deaths.append(float(r.get("t", 0.0)))
            elif r.get("op") == "boot":
                self._boots += 1

    def _append_locked(self, record: dict) -> None:
        self._seq += 1
        record["seq"] = self._seq
        record["incarnation"] = self.incarnation
        try:
            self.store.append(record)
        except Exception as exc:  # noqa: BLE001
            report_exception(
                "containment.crashloop.append",
                exc,
                registry=self.registry,
            )

    # ---- decision contract (PR 15) ----

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The COMPLETE evidence :meth:`decide` reads, as one pure
        JSON-shaped dict (decision-observatory contract)."""
        if now is None:
            now = self.clock()
        with self._lock:
            return {
                "now": float(now),
                "deaths": [float(t) for t in self._deaths[-64:]],
                "boots": self._boots,
                "k": self.k,
                "horizon_s": self.horizon_s,
                "base_backoff_s": self.base_backoff_s,
                "max_backoff_s": self.max_backoff_s,
                "brownout_cap": self.degraded_brownout_cap,
            }

    @staticmethod
    def decide(inputs: dict):
        """Pure boot-governor decision from a snapshot — ``(action,
        state)``. K rapid deaths within the horizon trigger exponential
        backoff (``base * 2**(rapid-k)``, capped) and a DEGRADED boot
        plan; fewer deaths decide nothing."""
        now = float(inputs["now"])
        horizon = float(inputs["horizon_s"])
        rapid = sum(
            1
            for t in inputs["deaths"]
            if now - float(t) <= horizon
        )
        k = int(inputs["k"])
        degraded = rapid >= k
        backoff = 0.0
        if degraded:
            backoff = min(
                float(inputs["base_backoff_s"]) * (2.0 ** (rapid - k)),
                float(inputs["max_backoff_s"]),
            )
        action = {
            "op": "backoff" if degraded else "none",
            "backoff_s": backoff,
            "degraded": degraded,
            "rapid_deaths": rapid,
        }
        state = {
            "blocked_until": now + backoff,
            "degraded": degraded,
        }
        return action, state

    # ---- ledger surface ----

    def note_boot(self, incarnation: Optional[str] = None) -> None:
        """A takeover completed recovery and holds the grant."""
        now = self.clock()
        with self._lock:
            if incarnation is not None:
                self.incarnation = incarnation
            self._boots += 1
            self._append_locked({"op": "boot", "t": float(now)})

    def note_death(
        self, incarnation: Optional[str] = None, reason: str = ""
    ) -> BootPlan:
        """An incarnation died (boot crash or mid-grant): journal it,
        snapshot once, decide purely, record on the decision ledger,
        arm the backoff gate. Returns the plan for the NEXT boot."""
        now = self.clock()
        with self._lock:
            if incarnation is not None:
                self.incarnation = incarnation
            self._deaths.append(float(now))
            self._append_locked(
                {"op": "death", "t": float(now), "reason": str(reason)[:256]}
            )
        inputs = self.snapshot(now)
        action, state = self.decide(inputs)
        plan = BootPlan(
            degraded=bool(action["degraded"]),
            backoff_s=float(action["backoff_s"]),
            rapid_deaths=int(action["rapid_deaths"]),
            brownout_cap=(
                self.degraded_brownout_cap if action["degraded"] else 0
            ),
            pipeline_depth=1 if action["degraded"] else 0,
            bisect_armed=bool(action["degraded"]),
        )
        with self._lock:
            self._blocked_until = float(state["blocked_until"])
            self._plan = plan
        dl = self.decisions
        if dl is not None:
            dl.record(
                "crashloop",
                len(inputs["deaths"]),
                inputs,
                action,
                state,
                outcome={"reason": str(reason)[:128]},
            )
        if plan.backoff_s > 0 and self.registry is not None:
            c = self.registry.get("crash_loop_backoffs_total")
            if c is not None:
                c.inc()
        return plan

    def may_boot(self, now: Optional[float] = None) -> bool:
        """Backoff gate for re-contention: False while inside the
        exponential boot backoff armed by the last death. Pure read —
        the DECISION was made (and recorded) at :meth:`note_death`."""
        if now is None:
            now = self.clock()
        with self._lock:
            return float(now) >= self._blocked_until

    def boot_plan(self) -> BootPlan:
        """The plan the next takeover should boot under (healthy default
        until K rapid deaths decide otherwise)."""
        with self._lock:
            return self._plan

    @property
    def boots(self) -> int:
        with self._lock:
            return self._boots

    @property
    def deaths(self) -> int:
        with self._lock:
            return len(self._deaths)


class StalenessWatchdog:
    """Detects connected-but-silent informer streams.

    Per check (driven from the caller's thread — the run loop or the
    soak's virtual clock; no background thread, so soak arms stay
    deterministic): every informer's observed rv is compared against its
    tracker's current rv. A stream that stays behind longer than
    ``horizon_s`` is STALE — the ``snapshot_freshness`` health row
    degrades, ``snapshot_staleness_seconds`` exports the oldest lag's
    age, and :meth:`stale` arms the controller snapshots (preemption,
    descheduler eviction, topology split refuse; plain placement
    continues — placing on slightly-old capacity self-corrects at
    commit revalidation, evicting a live workload on silence does not).

    The lag test is rv-based, not wall-clock-based: a QUIET stream (no
    events published) is fresh by definition — silence is only gray
    failure when the tracker moved and the informer did not.
    """

    def __init__(
        self,
        horizon_s: float = 5.0,
        clock=None,
        health=None,
        registry=None,
    ):
        self.horizon_s = float(horizon_s)
        self.clock = clock or _time.monotonic
        self.health = health
        self.registry = registry
        self._hub = None
        #: informer name -> time its lag was first observed
        self._behind: Dict[str, float] = {}
        self._stale = False
        self._max_age = 0.0
        #: informer name -> {"lag": rv delta, "age_s": seconds behind}
        self.last_report: Dict[str, dict] = {}

    def watch_hub(self, hub) -> "StalenessWatchdog":
        """Observe every informer the hub has wired (re-reads
        ``hub.informers`` each check, so informers wired later — or a
        takeover's fresh set — are picked up automatically)."""
        self._hub = hub
        return self

    def check(self, now: Optional[float] = None) -> float:
        """One freshness sweep. Returns the oldest stream's staleness
        age in seconds (0.0 = every stream fresh)."""
        if now is None:
            now = self.clock()
        now = float(now)
        informers = list(self._hub.informers) if self._hub is not None else []
        live = set()
        report: Dict[str, dict] = {}
        max_age = 0.0
        for inf in informers:
            name = inf.name
            live.add(name)
            lag = inf.tracker.version() - inf.observed_rv()
            if lag <= 0:
                self._behind.pop(name, None)
                continue
            since = self._behind.setdefault(name, now)
            age = now - since
            report[name] = {"lag": int(lag), "age_s": age}
            max_age = max(max_age, age)
        # informers detached since the last check must not pin staleness
        for name in list(self._behind):
            if name not in live:
                self._behind.pop(name, None)
        self._max_age = max_age
        self.last_report = report
        self._stale = max_age > self.horizon_s
        if self.registry is not None:
            g = self.registry.get("snapshot_staleness_seconds")
            if g is not None:
                g.set(max_age)
        if self.health is not None:
            if self._stale:
                worst = sorted(
                    report, key=lambda n: -report[n]["age_s"]
                )[:3]
                self.health.set(
                    "snapshot_freshness",
                    False,
                    f"{len(report)} informer stream(s) silent behind "
                    f"their tracker > {self.horizon_s}s: "
                    + ", ".join(worst),
                )
            else:
                self.health.set("snapshot_freshness", True)
        return max_age

    def stale(self) -> bool:
        """Verdict of the LAST check — the single snapshot-able bit the
        controller snapshots fold in (koordlint ``staleness-snapshot``:
        controllers read this through their snapshot, never ad hoc)."""
        return self._stale

    @property
    def staleness_seconds(self) -> float:
        return self._max_age
