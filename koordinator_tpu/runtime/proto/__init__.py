"""Generated protobuf message code (protoc --python_out)."""
