"""Horizontally partitioned control plane (PR 6 tentpole).

PR 5 made ONE leader survive crashes; production traffic needs N
schedulers live at once. This module partitions node ownership into S
**shards** — each with its own fencing epoch, lease, and write-ahead
journal — so N scheduler incarnations each own a disjoint shard set and
run their existing pipelined pumps concurrently, fenced per shard by the
exact machinery PR 5 built globally:

* :class:`ShardMap` — stable hash partition of node names (and quota
  names: a quota's pods all route to its HOME shard so one ledger owns
  the charge).
* :class:`ShardFabric` — the durable substrate that outlives any
  incarnation: per-shard :class:`~..core.journal.EpochFence` + journal
  store + lease lock, the cross-shard :class:`~..core.journal.ClaimTable`
  and the membership heartbeat table.
* :class:`ShardedScheduler` — one incarnation. Per shard it runs a
  :class:`~.ha.LeaderCoordinator` whose ``sched_factory`` builds the
  shard runtime lazily on takeover (shard-scoped snapshot wired through
  the statehub's ``node_filter``, a per-shard ``BindJournal``, the
  pipelined :class:`~..scheduler.stream.StreamScheduler` pump) and whose
  ``acquire_gate`` implements **multi-standby election**: candidates
  rank themselves by rendezvous hash over the LIVE membership, so a dead
  incarnation's shards spread deterministically across survivors instead
  of dogpiling whoever ticks first.
* :class:`ShardRouter` — routes a pending pod to the shard owning its
  feasible nodes (explicit node → that node's shard; quota-labeled →
  the quota's home shard; otherwise uid hash), optionally fanning out to
  a spill shard under backlog pressure. Fan-out is safe because every
  pump feeds a pod only after winning its **single-winner claim**
  (:class:`~..core.journal.ClaimTable`, epoch-fenced per shard) — two
  shards can never bind the same pod.

**Shard handoff** is the PR 5 recovery path scoped to one shard: the
donor drains its pump through the (already revoked) fence, surfaces its
queue for re-routing, and detaches only its own informers; the new owner
replays the shard's journal against a fresh shard-scoped snapshot and is
granted the shard's next epoch only after the resident state proves
bit-exact. The donor's OTHER shards keep serving throughout.
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from ..chaos import NULL_INJECTOR
from ..core.journal import BindJournal, ClaimTable, EpochFence, StaleEpochError
from ..utils import stable_hash as _stable_hash
from ..utils.leaderelection import (
    LeaderElector,
    LeaseLockSet,
    preferred_candidate,
)
from .ha import LeaderCoordinator


class ShardMap:
    """Stable partition of node ownership into ``n_shards`` shards."""

    def __init__(self, n_shards: int):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)

    def shard_of_node(self, node_name: str) -> int:
        return _stable_hash(f"node|{node_name}") % self.n_shards

    def shard_of_key(self, key: str) -> int:
        return _stable_hash(f"key|{key}") % self.n_shards

    def node_filter(self, shard: int) -> Callable[[str], bool]:
        """Predicate scoping a statehub wiring to one shard's nodes."""

        def owned(name: str, _s: int = int(shard)) -> bool:
            return self.shard_of_node(name) == _s

        return owned

    def partition(self, node_names: Sequence[str]) -> Dict[int, List[str]]:
        out: Dict[int, List[str]] = {s: [] for s in range(self.n_shards)}
        for name in node_names:
            out[self.shard_of_node(name)].append(name)
        return out


class Membership:
    """Heartbeat table of live scheduler incarnations (the analog of the
    per-instance presence Lease every control-plane replica keeps). The
    rendezvous election ranks only LIVE members, so a crashed
    incarnation drops out of every shard's candidate ranking one TTL
    after its last heartbeat — exactly when its shard leases start
    lapsing."""

    def __init__(self, ttl_s: float, clock: Callable[[], float] = _time.time):
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._beats: Dict[str, float] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def heartbeat(self, member: str) -> None:
        with self._lock:
            self._beats[member] = self._clock()

    def alive(self) -> List[str]:
        now = self._clock()
        with self._lock:
            return sorted(
                m for m, t in self._beats.items() if now - t <= self.ttl_s
            )

    def forget(self, member: str) -> None:
        with self._lock:
            self._beats.pop(member, None)


class ShardFabric:
    """The durable substrate of a partitioned control plane — everything
    that must outlive any single scheduler incarnation: per-shard
    fences, journal stores and lease locks, the cross-shard claim table,
    and the membership heartbeat table. In-process this is one shared
    object; a real deployment backs the same shapes with files/leases."""

    def __init__(
        self,
        n_shards: int,
        clock: Callable[[], float] = _time.time,
        journal_stores: Optional[Dict[int, object]] = None,
        claim_store=None,
        membership_ttl_s: float = 3.0,
        flight_stores: Optional[Dict[int, object]] = None,
        handoff_log_cap: int = 1024,
    ):
        from ..core.journal import MemoryJournalStore

        self.shard_map = ShardMap(n_shards)
        self.n_shards = int(n_shards)
        self.clock = clock
        self.fences: Dict[int, EpochFence] = {
            s: EpochFence() for s in range(n_shards)
        }
        self.journal_stores: Dict[int, object] = journal_stores or {
            s: MemoryJournalStore() for s in range(n_shards)
        }
        #: per-shard flight-recorder stores (fleet-tracing PR): the
        #: crash-surviving black box lives BESIDE the shard's journal —
        #: same durability substrate, so a takeover that can replay the
        #: journal can also read the dead owner's last-N cycle summaries
        self.flight_stores: Dict[int, object] = flight_stores or {
            s: MemoryJournalStore() for s in range(n_shards)
        }
        #: fleet-tracing PR: seam-matched shard-handoff instants, shared
        #: across incarnations like the stores — the donor logs its
        #: drain (``t_out``, ``t_in`` None) and the takeover completes
        #: the open seam (``t_in``/``to``), so the merged Chrome trace
        #: draws ONE flow arrow spanning the ownership gap. Stamps read
        #: the runtimes' TRACER clock (not the fabric's lease clock) so
        #: arrows land on the span time axis. Bounded like every other
        #: retention surface (tracer ring, flight recorder, lifecycle
        #: eviction): the oldest seams fall off a full deque, so a
        #: fleet rebalancing for months cannot grow the fabric.
        self.handoff_log: Deque[dict] = deque(maxlen=int(handoff_log_cap))  # guarded-by: self.handoff_lock
        #: guards the seam log's find-then-close read-modify-write: the
        #: log is shared across incarnations (possibly on different
        #: threads) and a deque raises if mutated mid-iteration
        self.handoff_lock = threading.Lock()
        self.locks = LeaseLockSet()
        self.claims = ClaimTable(claim_store, clock=clock)
        self.membership = Membership(membership_ttl_s, clock=clock)

    def shard_lease_lock(self, shard: int):
        return self.locks.lock(f"shard-{int(shard)}")


class ShardRouter:
    """Routes pending pods to shards.

    * explicit ``spec.node_name`` → that node's shard (its only feasible
      node lives there);
    * quota-labeled → the quota's HOME shard (one ledger owns the
      charge; reservations/quotas crossing shards are exactly why the
      fast-path journal exception had to close);
    * otherwise → uid hash, optionally fanned out to a spill shard when
      the primary's backlog exceeds ``spill_backlog`` — safe because the
      pumps' single-winner claim arbitrates feed time.
    """

    def __init__(
        self,
        shard_map: ShardMap,
        quota_of=None,
        spill_backlog: Optional[int] = None,
        lifecycle=None,
    ):
        self.shard_map = shard_map
        if quota_of is None:
            from ..scheduler.plugins.elasticquota import quota_name_of

            quota_of = quota_name_of
        self.quota_of = quota_of
        self.spill_backlog = spill_backlog
        #: fleet-tracing PR: when wired, route/fan-out decisions become
        #: lifecycle events (pods the tracker never saw get their
        #: ``submit`` anchor here — the router IS the control plane's
        #: front door for fresh pods)
        self.lifecycle = lifecycle

    def route(self, pod) -> int:
        if pod.spec.node_name:
            shard = self.shard_map.shard_of_node(pod.spec.node_name)
            detail = "node-pinned"
        else:
            leaf = self.quota_of(pod)
            if leaf is not None:
                shard = self.shard_map.shard_of_key(f"quota:{leaf}")
                detail = f"quota-home:{leaf}"
            else:
                shard = self.shard_map.shard_of_key(pod.meta.uid)
                detail = "uid-hash"
        lc = self.lifecycle
        if lc is not None:
            if not lc.seen(pod.meta.uid):
                lc.submitted(pod.meta.uid)
            lc.routed(pod.meta.uid, shard, detail=detail)
        return shard

    def targets(self, pod, backlog_of=None) -> List[int]:
        """Shards to enqueue the pod on: ``[primary]`` normally,
        ``[primary, spill]`` when the primary is backlogged and the pod
        is free to move (not quota-homed, not node-pinned)."""
        primary = self.route(pod)
        if (
            self.spill_backlog is None
            or backlog_of is None
            or self.shard_map.n_shards < 2
            or pod.spec.node_name
            or self.quota_of(pod) is not None
            or backlog_of(primary) < self.spill_backlog
        ):
            return [primary]
        spill = (primary + 1) % self.shard_map.n_shards
        if self.lifecycle is not None:
            self.lifecycle.event(
                pod.meta.uid, "fanout", shard=spill,
                detail=f"primary-backlog>{self.spill_backlog}",
            )
        return [primary, spill]


@dataclass
class ShardRuntime:
    """One shard being served by one incarnation."""

    shard: int
    sched: object
    stream: object
    informers: list
    node_filter: Callable[[str], bool]


@dataclass
class ShardHandoff:
    """What a donor surfaces when a shard's ownership leaves it."""

    shard: int
    #: decisions the drain still produced (fence held → real decisions)
    decided: List[Tuple[object, Optional[str], float]] = field(
        default_factory=list
    )
    #: (pod, arrival, tries) entries for the new owner's queue
    queued: List[Tuple[object, float, int]] = field(default_factory=list)


class ShardedScheduler:
    """One scheduler incarnation of a horizontally partitioned control
    plane: elects per-shard, builds shard runtimes lazily on takeover,
    pumps every owned shard each cycle, and hands shards off — queue
    intact, fence respected — when the rendezvous ranking or a lost
    lease says so.

    ``make_scheduler(shard, snapshot, fence, journal)`` builds the
    shard-scoped BatchScheduler (the caller owns quotas/devices/numa
    wiring); everything else — statehub informers, stream pump,
    election, recovery — is composed here.
    """

    def __init__(
        self,
        name: str,
        hub,
        fabric: ShardFabric,
        make_scheduler,
        pipelined: bool = True,
        max_batch: int = 256,
        max_retries: int = 8,
        lease_duration: float = 3.0,
        renew_deadline: float = 2.0,
        retry_period: float = 0.5,
        verify_recovery: bool = True,
        chaos=None,
        clock: Optional[Callable[[], float]] = None,
        lifecycle=None,
        slo=None,
        flight_capacity: int = 256,
        claim_tombstone_retention_s: float = 3600.0,
    ):
        self.name = name
        self.hub = hub
        self.fabric = fabric
        self.make_scheduler = make_scheduler
        self.pipelined = pipelined
        self.max_batch = max_batch
        self.max_retries = max_retries
        self.verify_recovery = verify_recovery
        self.chaos = chaos or NULL_INJECTOR
        self.clock = clock or fabric.clock
        self.dead = False
        #: distributed observability (fleet-tracing PR): the shared
        #: per-pod lifecycle tracker and per-shard SLO tracker this
        #: incarnation's streams/recovery feed; per-shard crash-surviving
        #: flight recorders (over ``fabric.flight_stores``) attach at
        #: runtime build. All optional — None keeps every hot path on
        #: the one-attribute-check disabled contract.
        self.lifecycle = lifecycle
        self.slo = slo
        self.flight_capacity = int(flight_capacity)
        #: ClaimTable tombstone retention (PR 6 queued follow-on): when a
        #: shard's run-loop journal compaction fires, settled claim
        #: tombstones OLDER than this window are compacted away; inside
        #: the window a post-GC claim on a settled uid still loses (a
        #: backlogged queue can hold a fanned-out copy past pod GC)
        self.claim_tombstone_retention_s = float(claim_tombstone_retention_s)
        self._runtimes: Dict[int, ShardRuntime] = {}
        self._handoffs: Dict[int, ShardHandoff] = {}
        self.stats = {
            "takeovers": 0,
            "handoffs": 0,
            "claims_lost": 0,
        }
        self._coords: Dict[int, LeaderCoordinator] = {}
        for s in range(fabric.n_shards):
            elector = LeaderElector(
                fabric.shard_lease_lock(s),
                identity=name,
                lease_duration=lease_duration,
                renew_deadline=renew_deadline,
                retry_period=retry_period,
                now_fn=self.clock,
                sleep_fn=lambda _dt: None,
            )
            self._coords[s] = LeaderCoordinator(
                sched_factory=self._factory(s),
                elector=elector,
                fence=fabric.fences[s],
                # no eager journal: _factory installs the runtime's own
                # BindJournal before recovery ever reads it, and an eager
                # instance would pay a full store.load() per (incarnation,
                # shard) at construction for nothing
                hub=hub,
                verify_recovery=verify_recovery,
                chaos=self.chaos,
                acquire_gate=self._gate(s),
                on_loss=self._teardown(s),
                recovery_pod_filter=self._pod_filter(s),
            )

    # ---- per-shard closures ----

    def _factory(self, shard: int):
        def build():
            rt = self._build_runtime(shard)
            # 3-tuple: recovery replays through the SAME journal
            # instance the runtime appends to (fresh view over the
            # shared store); pipeline None — the stream drains its own
            return rt.sched, None, rt.sched.bind_journal

        return build

    def _gate(self, shard: int):
        def designated() -> bool:
            alive = set(self.fabric.membership.alive())
            alive.add(self.name)
            return (
                preferred_candidate(alive, f"shard-{shard}") == self.name
            )

        return designated

    def _pod_filter(self, shard: int):
        flt = self.fabric.shard_map.node_filter(shard)

        def owned(pod) -> bool:
            return bool(pod.spec.node_name) and flt(pod.spec.node_name)

        return owned

    def _teardown(self, shard: int):
        def on_loss(_drained) -> None:
            rt = self._runtimes.pop(shard, None)
            if rt is None:
                return
            handoff = self._handoffs.setdefault(shard, ShardHandoff(shard))
            # the stream drains its pipeline through the revoked fence
            # (speculation discarded, trailing commit rejected with
            # STALE_LEADER_EPOCH) and requeues without burning retries
            handoff.decided.extend(rt.stream.drain_for_handoff())
            handoff.queued.extend(rt.stream.extract_queued())
            rt.stream.close()
            # only THIS shard's informers die; the incarnation's other
            # shards keep serving
            self.hub.detach(rt.informers)
            self.stats["handoffs"] += 1
            # open the handoff seam on the shared log: the takeover side
            # (_note_takeover, possibly on ANOTHER incarnation) closes it
            with self.fabric.handoff_lock:
                self.fabric.handoff_log.append(
                    {
                        "shard": shard,
                        "t_out": rt.sched.extender.tracer.clock(),
                        "t_in": None,
                        "from": self.name,
                        "to": "",
                    }
                )

        return on_loss

    def _build_runtime(self, shard: int) -> ShardRuntime:
        from ..core.snapshot import ClusterSnapshot
        from ..obs.flightrecorder import FlightRecorder

        flt = self.fabric.shard_map.node_filter(shard)
        snap = ClusterSnapshot()
        journal = BindJournal(
            self.fabric.journal_stores[shard], chaos=self.chaos, shard=shard
        )
        sched = self.make_scheduler(
            shard=shard,
            snapshot=snap,
            fence=self.fabric.fences[shard],
            journal=journal,
        )
        # crash-surviving flight recorder: the per-cycle black box lives
        # over the FABRIC's per-shard store (beside the journal), so
        # building a runtime here ADOPTS whatever tail the shard's dead
        # previous owner left — /debug/flightrecorder on the takeover
        # serves the last-N cycles of the incarnation that crashed
        sched.attach_flight_recorder(
            FlightRecorder(
                self.fabric.flight_stores[shard],
                capacity=self.flight_capacity,
                shard=shard,
                incarnation=self.name,
                clock=self.clock,
            )
        )
        # ClaimTable tombstone GC rides the shard journal's run-loop
        # compaction beat (PR 6 queued follow-on): compact settled
        # tombstones past the retention window, then publish the live
        # count so growth is observable (claim_tombstones_live)
        def _gc_claims(_sched=sched):
            live = self.fabric.claims.gc_tombstones(
                self.claim_tombstone_retention_s, now=self.clock()
            )
            _sched.extender.registry.get("claim_tombstones_live").set(
                float(live)
            )

        sched.on_journal_compacted = _gc_claims
        informers = self.hub.wire_scheduler(sched, node_filter=flt)
        self.hub.start()
        stream_cls = self._stream_cls()
        stream = stream_cls(
            sched,
            max_batch=self.max_batch,
            max_retries=self.max_retries,
            pipelined=self.pipelined,
            feed_gate=lambda pod, _s=shard: self._claim(_s, pod),
            lifecycle=self.lifecycle,
            slo=self.slo,
            shard=shard,
        )
        rt = ShardRuntime(
            shard=shard,
            sched=sched,
            stream=stream,
            informers=informers,
            node_filter=flt,
        )
        self._runtimes[shard] = rt
        return rt

    @staticmethod
    def _stream_cls():
        from ..scheduler.stream import StreamScheduler

        return StreamScheduler

    def _claim(self, shard: int, pod) -> bool:
        """Single-winner claim at feed time, stamped with OUR held epoch
        for the shard. Returns False ONLY when another shard genuinely
        won the pod's claim (safe to drop — the winner schedules it).
        A deposed owner's stamp raises :class:`StaleEpochError` instead,
        which the stream's batch collection treats as "keep the pod
        queued for the handoff": nobody else holds an unclaimed pod, so
        dropping it here would lose it forever."""
        rt = self._runtimes.get(shard)
        if rt is None:
            raise StaleEpochError(-1, 0, what="claim epoch")
        won = self.fabric.claims.claim(
            pod.meta.uid, shard, rt.sched._fence_epoch
        )
        if not won:
            self.stats["claims_lost"] += 1
        if self.lifecycle is not None:
            self.lifecycle.event(
                pod.meta.uid,
                "claim" if won else "claim_lost",
                shard=shard,
            )
        return won

    # ---- public surface ----

    def owned(self) -> List[int]:
        return sorted(
            s for s, c in self._coords.items() if c.leading
        )

    def owns(self, shard: int) -> bool:
        return self._coords[shard].leading

    def runtime(self, shard: int) -> Optional[ShardRuntime]:
        return self._runtimes.get(shard)

    def last_recovery(self, shard: int):
        return self._coords[shard].last_recovery

    def backlog(self, shard: int) -> int:
        rt = self._runtimes.get(shard)
        return rt.stream.backlog() if rt is not None else 0

    def fleet(self):
        """The incarnation's fleet-aggregation surface (one ``/metrics``
        scrape with a ``shard`` label, merged Chrome trace, per-shard
        ownership/epoch ``/healthz`` rows, ``/slo``,
        ``/debug/flightrecorder``). Read-only over live ownership —
        build on demand, never cached."""
        from ..obs.fleet import FleetServices

        return FleetServices(self)

    def tick(self) -> Dict[int, ShardHandoff]:
        """One election step across every shard: heartbeat, renew owned
        leases, voluntarily hand off shards whose rendezvous-designated
        owner is someone else alive, contend (gated) for free shards.
        Returns the handoffs surfaced this tick — their queued pods are
        the router's to re-place."""
        if self.dead:
            return {}
        self.fabric.membership.heartbeat(self.name)
        for s, coord in self._coords.items():
            if coord.leading and not self._gate(s)():
                # rebalance: a preferred live candidate exists (e.g. a
                # restarted incarnation rejoined) — voluntary handoff
                coord.step_down()
                continue
            was = coord.leading
            coord.tick()
            if coord.leading and not was:
                self.stats["takeovers"] += 1
                self._note_takeover(s, coord)
        out, self._handoffs = self._handoffs, {}
        return out

    @property
    def handoff_log(self) -> List[dict]:
        """The FLEET's seam-matched handoff instants (shared on the
        fabric): the flow-arrow feed for the merged Chrome trace.
        A snapshot — another incarnation may be appending a seam while
        a /trace render iterates, and a deque refuses that mix."""
        with self.fabric.handoff_lock:
            return [dict(e) for e in self.fabric.handoff_log]

    def _note_takeover(self, shard: int, coord) -> None:
        """Observability bookkeeping for a takeover that just recovered:
        one time-to-recover SLO sample, and the takeover instant closing
        the shard's OPEN handoff seam on the fabric's shared log (the
        donor's ``t_out`` was logged — possibly by another incarnation —
        at drain time; a crash takeover has no drained seam to close and
        logs a point entry instead).

        The shard's FIRST-ever grant (fence epoch 1) is a cold start,
        not a takeover: no handoff entry (nothing was handed off — the
        startup fleet would otherwise render one spurious arrow per
        shard) and no ``recovery`` SLO sample (the cold statehub sync is
        the slowest recovery there is; sampling it would burn the
        failover error budget before any failover happened)."""
        rt = self._runtimes.get(shard)
        now = (
            rt.sched.extender.tracer.clock()
            if rt is not None
            else self.clock()
        )
        with self.fabric.handoff_lock:
            for entry in reversed(self.fabric.handoff_log):
                if entry["shard"] == shard and entry["t_in"] is None:
                    entry["t_in"] = max(now, entry["t_out"])
                    entry["to"] = self.name
                    break
            else:
                if self.fabric.fences[shard].current() <= 1:
                    return  # cold start: not a takeover
                self.fabric.handoff_log.append(
                    {
                        "shard": shard,
                        "t_out": now,
                        "t_in": now,
                        "from": "",
                        "to": self.name,
                    }
                )
        rec = coord.last_recovery
        if self.slo is not None and rec is not None:
            self.slo.observe_recovery(shard, rec.duration_s)

    def submit(self, shard: int, pod, now: Optional[float] = None) -> bool:
        rt = self._runtimes.get(shard)
        if rt is None or not self._coords[shard].leading:
            return False
        rt.stream.submit(pod, now=now)
        return True

    def resubmit(
        self, shard: int, pod, arrival: float, tries: int
    ) -> bool:
        """Handoff path: enqueue with the original arrival stamp/retry
        budget from the donor's queue."""
        rt = self._runtimes.get(shard)
        if rt is None or not self._coords[shard].leading:
            return False
        rt.stream.resubmit(pod, arrival, tries)
        return True

    def pump(self) -> List[Tuple[int, object, Optional[str], float]]:
        """One pump over every owned shard (deterministic shard order).
        Returns ``(shard, pod, node|None, latency)`` decisions.

        Decided pods' claims are deliberately NOT released here: a
        fanned-out pod may still sit in another shard's queue, and a
        released claim would let that stale copy re-claim and
        double-schedule it. The driver releases at pod deletion (the
        apiserver GC analog) — and even then the ClaimTable keeps a
        TOMBSTONE, because a backlogged queue can hold a copy past the
        pod's GC; a post-release claim loses, so the copy is dropped."""
        decided: List[Tuple[int, object, Optional[str], float]] = []
        for s in sorted(self._runtimes):
            rt = self._runtimes[s]
            for pod, node, lat in rt.stream.pump():
                decided.append((s, pod, node, lat))
        return decided

    def flush(self) -> List[Tuple[int, object, Optional[str], float]]:
        decided: List[Tuple[int, object, Optional[str], float]] = []
        for s in sorted(self._runtimes):
            rt = self._runtimes[s]
            for pod, node, lat in rt.stream.flush():
                decided.append((s, pod, node, lat))
        return decided

    def kill(self) -> List[Tuple[int, object]]:
        """Simulated process death: every runtime's state dies WITHOUT a
        drain (no handoff — that is the point), informers are detached
        (the watches died with the process), leases are left to lapse.
        Returns ``(shard, pod)`` for every pod that was queued in the
        dead pumps — the driver reconciles them against the journals
        once the shards' new owners recover."""
        orphans: List[Tuple[int, object]] = []
        for s, rt in sorted(self._runtimes.items()):
            # event=None: a killed queue is NOT a graceful drain — the
            # timeline records orphan (below), never a handoff
            for pod, _arr, _tries in rt.stream.extract_queued(event=None):
                orphans.append((s, pod))
                if self.lifecycle is not None:
                    # the owner died with the pod queued: the timeline
                    # must bracket the dead incarnation (a later
                    # resubmit/enqueue on the new owner bridges it)
                    self.lifecycle.event(
                        pod.meta.uid, "orphan", shard=s, detail=self.name
                    )
            rt.stream.close()
            self.hub.detach(rt.informers)
            self._coords[s].leading = False
            self._coords[s].sched = None
            self._coords[s].pipeline = None
        self._runtimes.clear()
        self._handoffs.clear()
        self.dead = True
        self.fabric.membership.forget(self.name)
        return orphans

    def close(self) -> Dict[int, ShardHandoff]:
        """Graceful shutdown: step down from every owned shard (lease
        RELEASED — successors take over immediately instead of waiting
        out the TTL the way a crash forces), leave the membership, and
        tear everything down. Returns the final handoffs — their queued
        pods are the router's to re-place — so a graceful close never
        strands work the way :meth:`kill` deliberately does."""
        for s, coord in sorted(self._coords.items()):
            if coord.leading:
                coord.step_down()  # releases the lease; on_loss drains
        for rt in self._runtimes.values():
            rt.stream.close()
            self.hub.detach(rt.informers)
        self._runtimes.clear()
        self.fabric.membership.forget(self.name)
        self.dead = True
        out, self._handoffs = self._handoffs, {}
        return out
