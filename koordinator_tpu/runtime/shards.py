"""Horizontally partitioned control plane (PR 6 tentpole).

PR 5 made ONE leader survive crashes; production traffic needs N
schedulers live at once. This module partitions node ownership into S
**shards** — each with its own fencing epoch, lease, and write-ahead
journal — so N scheduler incarnations each own a disjoint shard set and
run their existing pipelined pumps concurrently, fenced per shard by the
exact machinery PR 5 built globally:

* :class:`ShardMap` — stable hash partition of node names (and quota
  names: a quota's pods all route to its HOME shard so one ledger owns
  the charge).
* :class:`ShardFabric` — the durable substrate that outlives any
  incarnation: per-shard :class:`~..core.journal.EpochFence` + journal
  store + lease lock, the cross-shard :class:`~..core.journal.ClaimTable`
  and the membership heartbeat table.
* :class:`ShardedScheduler` — one incarnation. Per shard it runs a
  :class:`~.ha.LeaderCoordinator` whose ``sched_factory`` builds the
  shard runtime lazily on takeover (shard-scoped snapshot wired through
  the statehub's ``node_filter``, a per-shard ``BindJournal``, the
  pipelined :class:`~..scheduler.stream.StreamScheduler` pump) and whose
  ``acquire_gate`` implements **multi-standby election**: candidates
  rank themselves by rendezvous hash over the LIVE membership, so a dead
  incarnation's shards spread deterministically across survivors instead
  of dogpiling whoever ticks first.
* :class:`ShardRouter` — routes a pending pod to the shard owning its
  feasible nodes (explicit node → that node's shard; quota-labeled →
  the quota's home shard; otherwise uid hash), optionally fanning out to
  a spill shard under backlog pressure. Fan-out is safe because every
  pump feeds a pod only after winning its **single-winner claim**
  (:class:`~..core.journal.ClaimTable`, epoch-fenced per shard) — two
  shards can never bind the same pod.

**Shard handoff** is the PR 5 recovery path scoped to one shard: the
donor drains its pump through the (already revoked) fence, surfaces its
queue for re-routing, and detaches only its own informers; the new owner
replays the shard's journal against a fresh shard-scoped snapshot and is
granted the shard's next epoch only after the resident state proves
bit-exact. The donor's OTHER shards keep serving throughout.
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from ..chaos import NULL_INJECTOR
from ..core.journal import BindJournal, ClaimTable, EpochFence, StaleEpochError
from ..utils import stable_hash as _stable_hash
from ..utils.leaderelection import (
    LeaderElector,
    LeaseLockSet,
    preferred_candidate,
)
from .ha import LeaderCoordinator


class ShardMap:
    """Stable — but now ELASTIC — partition of node ownership.

    The deploy-time shape is ``n_shards`` **base cells** (hash modulo,
    bit-identical to the PR 6 static map). The elastic-topology PR makes
    the partition a prefix-free CELL TREE over those cells: splitting an
    active shard replaces its cell with two child cells (each node
    descends by an independent per-depth hash bit, so exactly the
    parent's nodes — and nothing else — re-home, split roughly in half),
    and merging two SIBLING cells re-unifies them under a fresh shard
    id. Shard ids are never reused: a retired id's cell path is kept so
    :meth:`cell_covers` can answer "was this node ever that shard's?"
    for decisions that raced a topology change.

    Reads are lock-free (the cell dict is swapped copy-on-write under
    ``_lock``); only topology transitions mutate.
    """

    #: a cell path: (base cell, bit, bit, ...) — prefix-free cover
    MAX_DEPTH = 62

    def __init__(self, n_shards: int):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.base = int(n_shards)
        self._cells: Dict[Tuple[int, ...], int] = {
            (i,): i for i in range(self.base)
        }  # guarded-by: self._lock
        #: every shard id EVER (active and retired) -> its cell path
        self._paths: Dict[int, Tuple[int, ...]] = {
            i: (i,) for i in range(self.base)
        }  # guarded-by: self._lock
        self._next_id = self.base
        #: topology generation: bumped by every committed split/merge
        self.generation = 0
        self._lock = threading.Lock()

    # ---- routing ----

    @property
    def n_shards(self) -> int:
        """ACTIVE shard count (== the deploy-time count until the first
        split commits)."""
        return len(self._cells)

    @staticmethod
    def _bit(kind: str, name: str, depth: int) -> int:
        """The per-depth descent bit: an independent hash per depth so a
        re-split of a merged range re-partitions the same way (stable
        across processes, like every routing hash here)."""
        return _stable_hash(f"{kind}|{name}|d{depth}") & 1

    def _locate(self, kind: str, name: str) -> int:
        cells = self._cells  # one read: topology swaps copy-on-write
        path: Tuple[int, ...] = (_stable_hash(f"{kind}|{name}") % self.base,)
        sid = cells.get(path)
        while sid is None:
            if len(path) > self.MAX_DEPTH:
                raise RuntimeError(
                    f"no cell covers {kind}|{name} (corrupt topology)"
                )
            path = path + (self._bit(kind, name, len(path) - 1),)
            sid = cells.get(path)
        return sid

    def shard_of_node(self, node_name: str) -> int:
        return self._locate("node", node_name)

    def shard_of_key(self, key: str) -> int:
        return self._locate("key", key)

    def node_filter(self, shard: int) -> Callable[[str], bool]:
        """Predicate scoping a statehub wiring to one shard's nodes."""

        def owned(name: str, _s: int = int(shard)) -> bool:
            return self.shard_of_node(name) == _s

        return owned

    def partition(self, node_names: Sequence[str]) -> Dict[int, List[str]]:
        out: Dict[int, List[str]] = {
            s: [] for s in self.active_shards()
        }
        for name in node_names:
            out[self.shard_of_node(name)].append(name)
        return out

    # ---- topology surface (elastic-topology PR) ----

    def active_shards(self) -> List[int]:
        return sorted(self._cells.values())

    def is_active(self, shard: int) -> bool:
        path = self._paths.get(int(shard))
        return path is not None and self._cells.get(path) == int(shard)

    def path_of(self, shard: int) -> Optional[Tuple[int, ...]]:
        return self._paths.get(int(shard))

    def cell_covers(self, shard: int, node_name: str) -> bool:
        """True when ``node_name`` falls inside the (possibly retired)
        shard's cell range — generation-independent truth, so a decision
        produced by a donor just before a split still attributes to the
        range it legitimately owned."""
        path = self._paths.get(int(shard))
        if path is None:
            return False
        if _stable_hash(f"node|{node_name}") % self.base != path[0]:
            return False
        return all(
            self._bit("node", node_name, d) == bit
            for d, bit in enumerate(path[1:])
        )

    def split_dest(
        self, parent: int, name: str, child0: int, child1: int,
        kind: str = "node",
    ) -> int:
        """Which child of a PLANNED split of ``parent`` will own
        ``name`` — computable before the split commits (the journal
        re-home and the non-empty-children guard both need the answer
        while the parent is still the active cell)."""
        path = self._paths[int(parent)]
        return child0 if self._bit(kind, name, len(path) - 1) == 0 else child1

    def allocate_ids(self, n: int) -> List[int]:
        """Fresh, never-reused shard ids for a planned transition. Ids
        burned by a rolled-back attempt stay burned — a stale child
        journal can then never be mistaken for a live shard's."""
        with self._lock:
            out = list(range(self._next_id, self._next_id + int(n)))
            self._next_id += int(n)
            return out

    def split_cells(
        self, parent: int, child0: int, child1: int
    ) -> None:
        """COMMIT a split: the parent's cell is replaced by two child
        cells (bit 0 → child0, bit 1 → child1). Only the topology
        transaction (:mod:`..runtime.elastic`) calls this, after the
        journal re-home succeeded."""
        with self._lock:
            path = self._paths.get(int(parent))
            if path is None or self._cells.get(path) != int(parent):
                raise ValueError(f"shard {parent} is not an active cell")
            cells = dict(self._cells)
            del cells[path]
            cells[path + (0,)] = int(child0)
            cells[path + (1,)] = int(child1)
            self._paths[int(child0)] = path + (0,)
            self._paths[int(child1)] = path + (1,)
            self._cells = cells
            self.generation += 1

    def merge_cells(self, a: int, b: int, merged: int) -> None:
        """COMMIT a merge of two SIBLING cells into one fresh shard id
        owning the parent range."""
        with self._lock:
            pa, pb = self._paths.get(int(a)), self._paths.get(int(b))
            if (
                pa is None
                or pb is None
                or self._cells.get(pa) != int(a)
                or self._cells.get(pb) != int(b)
                or len(pa) < 2
                or pa[:-1] != pb[:-1]
                or {pa[-1], pb[-1]} != {0, 1}
            ):
                raise ValueError(
                    f"shards {a}/{b} are not active sibling cells"
                )
            cells = dict(self._cells)
            del cells[pa]
            del cells[pb]
            parent_path = pa[:-1]
            cells[parent_path] = int(merged)
            self._paths[int(merged)] = parent_path
            self._cells = cells
            self.generation += 1

    def successors(self, shard: int) -> List[int]:
        """The ACTIVE shards whose ranges overlap a (possibly retired)
        shard's cell — where that shard's journal live set was re-homed
        to. A merge has one successor (the merged cell), a split has
        two; an active shard is its own sole successor. Crash-orphan
        reconciliation reads this: a binding journaled on a since-
        retired shard is recovered by whichever successor owns its
        node."""
        path = self._paths.get(int(shard))
        if path is None:
            return []
        cells = self._cells
        out = [
            sid
            for p, sid in cells.items()
            if p[: len(path)] == path or path[: len(p)] == p
        ]
        return sorted(out)

    def siblings(self) -> List[Tuple[int, int]]:
        """Active sibling cell pairs ``(bit0_shard, bit1_shard)`` — the
        merge candidates (only a split can be undone; the deploy-time
        base cells are the scale-in floor)."""
        cells = self._cells
        out: List[Tuple[int, int]] = []
        for path, sid in cells.items():
            if len(path) >= 2 and path[-1] == 0:
                other = cells.get(path[:-1] + (1,))
                if other is not None:
                    out.append((sid, other))
        return sorted(out)


def transition_shards(intent: dict) -> set:
    """Every shard id an open topology transition touches (donors AND
    planned children) — none of them is electable while it is open."""
    out = set()
    for key in ("parent", "a", "b", "merged"):
        if intent.get(key) is not None:
            out.add(int(intent[key]))
    for child in intent.get("children", ()):
        out.add(int(child))
    return out


class ShardTopology:
    """Journaled, generation-numbered shard-map transitions (the
    elastic-topology tentpole's durable record).

    Every split/merge is a two-record transaction over the same store
    API the bind journals use: an ``*_intent`` record (gen = highest
    generation ever journaled + 1) BEFORE any re-homing mutates shared
    state, then either a ``*_commit`` (the :class:`ShardMap` cells swap
    and the generation advances) or a ``rollback`` (the attempt's child
    ids stay burned, the parent generation stays active). Generations
    are **epoch-monotonic at the storage boundary**: an intent stamped
    at or below the journaled high raises :class:`StaleEpochError` —
    the same fencing-token-on-shared-store discipline the bind journal
    enforces — and only ONE transition may be open at a time (a
    half-owned range can never exist, even across racing controllers).

    Reload replays committed transitions onto the map; a trailing open
    intent is VOID (the splitting process died mid-transaction — the
    parent generation is still the active one, exactly the rollback the
    in-process crash path journals explicitly)."""

    def __init__(self, shard_map: ShardMap, store=None):
        from ..core.journal import MemoryJournalStore

        self.map = shard_map
        self.store = store if store is not None else MemoryJournalStore()
        self._lock = threading.Lock()
        self._seq = 0
        self._gen_high = 0
        self._open: Optional[dict] = None  # guarded-by: self._lock
        for rec in sorted(
            self.store.load(), key=lambda r: r.get("seq", 0)
        ):
            self._seq = max(self._seq, int(rec.get("seq", 0)))
            self._gen_high = max(self._gen_high, int(rec.get("gen", 0)))
            op = rec.get("op")
            if op in ("split_intent", "merge_intent"):
                self._open = dict(rec)
                # keep id allocation ahead of every journaled attempt
                ids = [int(i) for i in rec.get("children", ())]
                ids.append(int(rec.get("merged", -1)))
                with self.map._lock:
                    self.map._next_id = max(
                        self.map._next_id, max(ids) + 1
                    )
            elif op == "split_commit" and self._open is not None:
                a, b = (int(i) for i in self._open["children"])
                self.map.split_cells(int(self._open["parent"]), a, b)
                self._open = None
            elif op == "merge_commit" and self._open is not None:
                self.map.merge_cells(
                    int(self._open["a"]),
                    int(self._open["b"]),
                    int(self._open["merged"]),
                )
                self._open = None
            elif op == "rollback":
                self._open = None
        # trailing open intent = crash mid-transaction: void by design
        self._open = None

    def _append_locked(self, rec: dict) -> dict:
        self._seq += 1
        rec = {"seq": self._seq, **rec}
        self.store.append(rec)
        return rec

    @property
    def generation(self) -> int:
        return self.map.generation

    def open_transition(self) -> Optional[dict]:
        with self._lock:
            return dict(self._open) if self._open is not None else None

    def begin_split(self, parent: int) -> dict:
        """Journal a split intent (fence-checked generation, fresh child
        ids). Raises :class:`StaleEpochError` on a stale generation and
        refuses to open a second transition while one is in flight."""
        from ..core.journal import StaleEpochError

        with self._lock:
            if self._open is not None:
                raise StaleEpochError(
                    self._gen_high + 1,
                    self._gen_high,
                    what="topology transition (one already open)",
                )
            if not self.map.is_active(int(parent)):
                raise ValueError(f"shard {parent} is not active")
            gen = self._gen_high + 1
            a, b = self.map.allocate_ids(2)
            rec = self._append_locked(
                {
                    "op": "split_intent",
                    "gen": gen,
                    "parent": int(parent),
                    "children": [a, b],
                    "path": list(self.map.path_of(int(parent))),
                }
            )
            self._gen_high = gen
            self._open = dict(rec)
            return dict(rec)

    def begin_merge(self, a: int, b: int) -> dict:
        from ..core.journal import StaleEpochError

        with self._lock:
            if self._open is not None:
                raise StaleEpochError(
                    self._gen_high + 1,
                    self._gen_high,
                    what="topology transition (one already open)",
                )
            if (int(a), int(b)) not in self.map.siblings():
                raise ValueError(
                    f"shards {a}/{b} are not mergeable siblings"
                )
            gen = self._gen_high + 1
            (merged,) = self.map.allocate_ids(1)
            rec = self._append_locked(
                {
                    "op": "merge_intent",
                    "gen": gen,
                    "a": int(a),
                    "b": int(b),
                    "merged": merged,
                }
            )
            self._gen_high = gen
            self._open = dict(rec)
            return dict(rec)

    def commit(self, intent: dict) -> None:
        """Close the open transition successfully: swap the map's cells
        and journal the commit — the generation the routers see advances
        HERE, never mid-re-home."""
        with self._lock:
            if self._open is None or self._open["gen"] != intent["gen"]:
                raise ValueError("no matching open topology transition")
            if self._open["op"] == "split_intent":
                a, b = (int(i) for i in self._open["children"])
                # record first, then swap: a failed append leaves the
                # map untouched (the intent stays open for rollback); a
                # crash between the two replays the commit on reload
                self._append_locked(
                    {
                        "op": "split_commit",
                        "gen": int(self._open["gen"]),
                        "parent": int(self._open["parent"]),
                        "children": [a, b],
                    }
                )
                self.map.split_cells(int(self._open["parent"]), a, b)
            else:
                self._append_locked(
                    {
                        "op": "merge_commit",
                        "gen": int(self._open["gen"]),
                        "merged": int(self._open["merged"]),
                    }
                )
                self.map.merge_cells(
                    int(self._open["a"]),
                    int(self._open["b"]),
                    int(self._open["merged"]),
                )
            self._open = None

    def rollback(self, intent: dict, reason: str = "") -> None:
        """Close the open transition WITHOUT touching the map: the
        parent generation stays active (never a half-owned range); the
        attempt's ids stay burned."""
        with self._lock:
            if self._open is None or self._open["gen"] != intent["gen"]:
                return  # already closed (idempotent crash cleanup)
            self._append_locked(
                {
                    "op": "rollback",
                    "gen": int(self._open["gen"]),
                    "reason": reason,
                }
            )
            self._open = None

    def history(self, limit: int = 64) -> List[dict]:
        return self.store.load()[-int(limit):]


class Membership:
    """Heartbeat table of live scheduler incarnations (the analog of the
    per-instance presence Lease every control-plane replica keeps). The
    rendezvous election ranks only LIVE members, so a crashed
    incarnation drops out of every shard's candidate ranking one TTL
    after its last heartbeat — exactly when its shard leases start
    lapsing."""

    def __init__(self, ttl_s: float, clock: Callable[[], float] = _time.time):
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._beats: Dict[str, float] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def heartbeat(self, member: str) -> None:
        with self._lock:
            self._beats[member] = self._clock()

    def alive(self) -> List[str]:
        now = self._clock()
        with self._lock:
            return sorted(
                m for m, t in self._beats.items() if now - t <= self.ttl_s
            )

    def forget(self, member: str) -> None:
        with self._lock:
            self._beats.pop(member, None)


class ShardFabric:
    """The durable substrate of a partitioned control plane — everything
    that must outlive any single scheduler incarnation: per-shard
    fences, journal stores and lease locks, the cross-shard claim table,
    and the membership heartbeat table. In-process this is one shared
    object; a real deployment backs the same shapes with files/leases."""

    def __init__(
        self,
        n_shards: int,
        clock: Callable[[], float] = _time.time,
        journal_stores: Optional[Dict[int, object]] = None,
        claim_store=None,
        membership_ttl_s: float = 3.0,
        flight_stores: Optional[Dict[int, object]] = None,
        handoff_log_cap: int = 1024,
        topology_store=None,
        decision_stores: Optional[Dict[int, object]] = None,
    ):
        from ..core.journal import MemoryJournalStore

        self.shard_map = ShardMap(n_shards)
        #: deploy-time base cell count (the scale-in floor); the LIVE
        #: shard count is :attr:`n_shards` / ``shard_map.active_shards()``
        self.base_shards = int(n_shards)
        #: elastic-topology PR: the journaled split/merge transition log
        #: — replaying it onto the fresh base map reconstructs the live
        #: generation, so the topology outlives any incarnation exactly
        #: like the per-shard journals do
        self.topology = ShardTopology(self.shard_map, store=topology_store)
        self.clock = clock
        self.fences: Dict[int, EpochFence] = {
            s: EpochFence() for s in range(n_shards)
        }
        self.journal_stores: Dict[int, object] = journal_stores or {
            s: MemoryJournalStore() for s in range(n_shards)
        }
        #: per-shard flight-recorder stores (fleet-tracing PR): the
        #: crash-surviving black box lives BESIDE the shard's journal —
        #: same durability substrate, so a takeover that can replay the
        #: journal can also read the dead owner's last-N cycle summaries
        self.flight_stores: Dict[int, object] = flight_stores or {
            s: MemoryJournalStore() for s in range(n_shards)
        }
        #: per-shard decision-ledger stores (decision-observatory PR):
        #: controller decisions persist BESIDE the journal and the
        #: flight recorder over the same sealed/screened store API, so a
        #: takeover adopts the dead owner's decision tail too
        self.decision_stores: Dict[int, object] = decision_stores or {
            s: MemoryJournalStore() for s in range(n_shards)
        }
        #: fleet-tracing PR: seam-matched shard-handoff instants, shared
        #: across incarnations like the stores — the donor logs its
        #: drain (``t_out``, ``t_in`` None) and the takeover completes
        #: the open seam (``t_in``/``to``), so the merged Chrome trace
        #: draws ONE flow arrow spanning the ownership gap. Stamps read
        #: the runtimes' TRACER clock (not the fabric's lease clock) so
        #: arrows land on the span time axis. Bounded like every other
        #: retention surface (tracer ring, flight recorder, lifecycle
        #: eviction): the oldest seams fall off a full deque, so a
        #: fleet rebalancing for months cannot grow the fabric.
        self.handoff_log: Deque[dict] = deque(maxlen=int(handoff_log_cap))  # guarded-by: self.handoff_lock
        #: guards the seam log's find-then-close read-modify-write: the
        #: log is shared across incarnations (possibly on different
        #: threads) and a deque raises if mutated mid-iteration
        self.handoff_lock = threading.Lock()
        self.locks = LeaseLockSet()
        # shard_live: a claim held by a RETIRED cell self-heals to the
        # live claimant (closes the commit→rehome crash window)
        self.claims = ClaimTable(
            claim_store, clock=clock, shard_live=self.shard_map.is_active
        )
        self.membership = Membership(membership_ttl_s, clock=clock)

    @property
    def n_shards(self) -> int:
        """LIVE shard count — tracks the topology generation (kept as a
        property so every pre-elastic consumer keeps reading the truth)."""
        return self.shard_map.n_shards

    def ensure_shard(self, shard: int) -> None:
        """Materialize the durable substrate for a shard id minted by a
        topology transition (child shards get fresh fences/stores — a
        fresh fence at epoch 0 is exactly what lets the journal re-home
        assert "no owner was ever granted here")."""
        from ..core.journal import MemoryJournalStore

        s = int(shard)
        if s not in self.fences:
            self.fences[s] = EpochFence()
        if s not in self.journal_stores:
            self.journal_stores[s] = MemoryJournalStore()
        if s not in self.flight_stores:
            self.flight_stores[s] = MemoryJournalStore()
        if s not in self.decision_stores:
            self.decision_stores[s] = MemoryJournalStore()

    def shard_lease_lock(self, shard: int):
        return self.locks.lock(f"shard-{int(shard)}")


class ShardRouter:
    """Routes pending pods to shards.

    * explicit ``spec.node_name`` → that node's shard (its only feasible
      node lives there);
    * quota-labeled → the quota's HOME shard (one ledger owns the
      charge; reservations/quotas crossing shards are exactly why the
      fast-path journal exception had to close);
    * otherwise → uid hash, optionally fanned out to a spill shard when
      the primary's backlog exceeds ``spill_backlog`` — safe because the
      pumps' single-winner claim arbitrates feed time.
    """

    def __init__(
        self,
        shard_map: ShardMap,
        quota_of=None,
        spill_backlog: Optional[int] = None,
        lifecycle=None,
        gang_of=None,
        spill_resume_frac: float = 0.5,
        burn_of=None,
        brownout=None,
        burn_spill_frac: float = 0.5,
    ):
        self.shard_map = shard_map
        if quota_of is None:
            from ..scheduler.plugins.elasticquota import quota_name_of

            quota_of = quota_name_of
        self.quota_of = quota_of
        if gang_of is None:
            from ..scheduler.plugins.coscheduling import gang_key_of

            gang_of = gang_key_of
        #: gang members route WHOLE to the gang's home shard (one
        #: PodGroupManager must see the whole gang for its min-member
        #: gate); a gang whose feasible nodes SPAN shards goes through
        #: the two-phase :class:`~.elastic.CrossShardGangCoordinator`
        #: instead of this router
        self.gang_of = gang_of
        self.spill_backlog = spill_backlog
        #: spill hysteresis (elastic-topology PR satellite): fan-out
        #: DISENGAGES only once the primary's backlog falls below
        #: ``spill_resume_frac * spill_backlog`` — a backlog oscillating
        #: around the threshold would otherwise toggle fan-out per pod,
        #: churning ClaimTable claims/tombstones for nothing
        self.spill_resume_frac = float(spill_resume_frac)
        self._spilling: Dict[int, bool] = {}  # guarded-by: self._spill_lock
        self._spill_lock = threading.Lock()
        #: overload-control PR (ROADMAP follow-on): fan-out consults the
        #: topology controller's BURN VIEW, not raw backlog alone —
        #: ``burn_of(shard)`` (e.g. ``TopologyController.shard_burn``)
        #: lowers the engage threshold to ``burn_spill_frac`` of
        #: ``spill_backlog`` while the primary burns its placement SLO
        #: budget (burn > 1), so a burning primary spills EARLIER than a
        #: merely busy one
        self.burn_of = burn_of
        self.burn_spill_frac = float(burn_spill_frac)
        #: …and a BROWNING fleet stops fanning out BATCH/FREE claims it
        #: is about to defer/shed (L3+): a spill claim for a pod the
        #: admission controller will park would churn the ClaimTable for
        #: nothing
        self.brownout = brownout
        #: fleet-tracing PR: when wired, route/fan-out decisions become
        #: lifecycle events (pods the tracker never saw get their
        #: ``submit`` anchor here — the router IS the control plane's
        #: front door for fresh pods)
        self.lifecycle = lifecycle

    def route(self, pod) -> int:
        if pod.spec.node_name:
            shard = self.shard_map.shard_of_node(pod.spec.node_name)
            detail = "node-pinned"
        else:
            gang = self.gang_of(pod)
            leaf = self.quota_of(pod)
            if gang is not None:
                shard = self.shard_map.shard_of_key(f"gang:{gang}")
                detail = f"gang-home:{gang}"
            elif leaf is not None:
                shard = self.shard_map.shard_of_key(f"quota:{leaf}")
                detail = f"quota-home:{leaf}"
            else:
                shard = self.shard_map.shard_of_key(pod.meta.uid)
                detail = "uid-hash"
        lc = self.lifecycle
        if lc is not None:
            if not lc.seen(pod.meta.uid):
                lc.submitted(pod.meta.uid)
            lc.routed(pod.meta.uid, shard, detail=detail)
        return shard

    def _spill_engaged(self, primary: int, backlog: int) -> bool:
        """Hysteresis band: engage at ``spill_backlog``, release only
        below ``spill_resume_frac`` of it. A BURNING primary (its
        placement burn rate > 1, read through the topology controller's
        view) engages at ``burn_spill_frac * spill_backlog`` instead —
        the burn says the backlog is not draining, so waiting for the
        raw threshold just converts queue depth into SLO debt. The
        RELEASE threshold is anchored at the burn-adjusted FLOOR
        whenever a burn view is wired, so an oscillating burn signal
        cannot move the release level and saw the band (the exact
        claim-churn flap the hysteresis exists to prevent)."""
        engage_at = self.spill_backlog
        floor = engage_at
        if self.burn_of is not None:
            floor = max(1, int(engage_at * self.burn_spill_frac))
            if self.burn_of(primary) > 1.0:
                engage_at = floor
        low = floor * self.spill_resume_frac
        with self._spill_lock:
            engaged = self._spilling.get(primary, False)
            if not engaged and backlog >= engage_at:
                engaged = True
            elif engaged and backlog < low:
                engaged = False
            self._spilling[primary] = engaged
            return engaged

    def targets(self, pod, backlog_of=None) -> List[int]:
        """Shards to enqueue the pod on: ``[primary]`` normally,
        ``[primary, spill]`` when the primary is backlogged and the pod
        is free to move (not quota-homed, not gang-homed, not
        node-pinned). The spill target is the NEXT active shard in the
        live topology (ids are sparse once splits happen)."""
        primary = self.route(pod)
        bo = self.brownout
        if (
            self.spill_backlog is None
            or backlog_of is None
            or self.shard_map.n_shards < 2
            or pod.spec.node_name
            or self.gang_of(pod) is not None
            or self.quota_of(pod) is not None
            # a browning fleet stops fanning out claims it will
            # defer/shed: the band's spill copy would be parked at the
            # spill shard's admission gate anyway
            or (bo is not None and bo.defers(pod.priority_class))
            or not self._spill_engaged(primary, backlog_of(primary))
        ):
            return [primary]
        active = self.shard_map.active_shards()
        spill = active[(active.index(primary) + 1) % len(active)]
        if self.lifecycle is not None:
            self.lifecycle.event(
                pod.meta.uid, "fanout", shard=spill,
                detail=f"primary-backlog>{self.spill_backlog}",
            )
        return [primary, spill]


@dataclass
class ShardRuntime:
    """One shard being served by one incarnation."""

    shard: int
    sched: object
    stream: object
    informers: list
    node_filter: Callable[[str], bool]


@dataclass
class ShardHandoff:
    """What a donor surfaces when a shard's ownership leaves it."""

    shard: int
    #: decisions the drain still produced (fence held → real decisions)
    decided: List[Tuple[object, Optional[str], float]] = field(
        default_factory=list
    )
    #: (pod, arrival, tries) entries for the new owner's queue
    queued: List[Tuple[object, float, int]] = field(default_factory=list)


class ShardedScheduler:
    """One scheduler incarnation of a horizontally partitioned control
    plane: elects per-shard, builds shard runtimes lazily on takeover,
    pumps every owned shard each cycle, and hands shards off — queue
    intact, fence respected — when the rendezvous ranking or a lost
    lease says so.

    ``make_scheduler(shard, snapshot, fence, journal)`` builds the
    shard-scoped BatchScheduler (the caller owns quotas/devices/numa
    wiring); everything else — statehub informers, stream pump,
    election, recovery — is composed here.
    """

    def __init__(
        self,
        name: str,
        hub,
        fabric: ShardFabric,
        make_scheduler,
        pipelined: bool = True,
        pipeline_depth: int = 1,
        max_batch: int = 256,
        max_retries: int = 8,
        lease_duration: float = 3.0,
        renew_deadline: float = 2.0,
        retry_period: float = 0.5,
        verify_recovery: bool = True,
        chaos=None,
        clock: Optional[Callable[[], float]] = None,
        lifecycle=None,
        slo=None,
        flight_capacity: int = 256,
        claim_tombstone_retention_s: float = 3600.0,
        overload=None,
        brownout=None,
        decision_capacity: int = 512,
        decisions: bool = True,
    ):
        self.name = name
        self.hub = hub
        self.fabric = fabric
        self.make_scheduler = make_scheduler
        self.pipelined = pipelined
        self.pipeline_depth = int(pipeline_depth)
        self.max_batch = max_batch
        self.max_retries = max_retries
        self.verify_recovery = verify_recovery
        self.chaos = chaos or NULL_INJECTOR
        self.clock = clock or fabric.clock
        self.dead = False
        #: distributed observability (fleet-tracing PR): the shared
        #: per-pod lifecycle tracker and per-shard SLO tracker this
        #: incarnation's streams/recovery feed; per-shard crash-surviving
        #: flight recorders (over ``fabric.flight_stores``) attach at
        #: runtime build. All optional — None keeps every hot path on
        #: the one-attribute-check disabled contract.
        self.lifecycle = lifecycle
        self.slo = slo
        #: QoS-aware overload control (overload-control PR): the fleet-
        #: shared AdmissionController each shard's stream consults at
        #: submit, and the BrownoutController whose ladder level gates
        #: the pipeline/bucket and the admission defers/sheds. Both
        #: optional — None keeps every hot path one attribute check.
        self.overload = overload
        self.brownout = brownout
        if overload is not None and brownout is None:
            # one wiring knob: the admission controller usually carries
            # its ladder
            self.brownout = overload.brownout
        self.flight_capacity = int(flight_capacity)
        #: decision observatory (decision-observatory PR): per-shard
        #: DecisionLedgers over ``fabric.decision_stores`` attach at
        #: runtime build (adoption = crash survival, like the flight
        #: recorder). ``decisions=False`` disables recording entirely —
        #: every controller site is back to one attribute-is-None check.
        self.decision_capacity = int(decision_capacity)
        self.decisions_enabled = bool(decisions)
        #: ClaimTable tombstone retention (PR 6 queued follow-on): when a
        #: shard's run-loop journal compaction fires, settled claim
        #: tombstones OLDER than this window are compacted away; inside
        #: the window a post-GC claim on a settled uid still loses (a
        #: backlogged queue can hold a fanned-out copy past pod GC)
        self.claim_tombstone_retention_s = float(claim_tombstone_retention_s)
        self._runtimes: Dict[int, ShardRuntime] = {}
        self._handoffs: Dict[int, ShardHandoff] = {}
        self.stats = {
            "takeovers": 0,
            "handoffs": 0,
            "claims_lost": 0,
        }
        self._elect_kw = {
            "lease_duration": lease_duration,
            "renew_deadline": renew_deadline,
            "retry_period": retry_period,
        }
        self._coords: Dict[int, LeaderCoordinator] = {}
        for s in fabric.shard_map.active_shards():
            self._coords[s] = self._make_coord(s)

    # ---- per-shard closures ----

    def _make_coord(self, shard: int) -> LeaderCoordinator:
        s = int(shard)
        self.fabric.ensure_shard(s)
        elector = LeaderElector(
            self.fabric.shard_lease_lock(s),
            identity=self.name,
            now_fn=self.clock,
            sleep_fn=lambda _dt: None,
            **self._elect_kw,
        )
        return LeaderCoordinator(
            sched_factory=self._factory(s),
            elector=elector,
            fence=self.fabric.fences[s],
            # no eager journal: _factory installs the runtime's own
            # BindJournal before recovery ever reads it, and an eager
            # instance would pay a full store.load() per (incarnation,
            # shard) at construction for nothing
            hub=self.hub,
            verify_recovery=self.verify_recovery,
            chaos=self.chaos,
            acquire_gate=self._gate(s),
            on_loss=self._teardown(s),
            recovery_pod_filter=self._pod_filter(s),
        )

    def _sync_topology(self) -> None:
        """Track the live topology (elastic-topology PR): a committed
        split/merge retires cells and mints new ones — every incarnation
        grows coordinators for the fresh shards (so the rendezvous
        election can seat their first owners) and retires coordinators
        for dead cells. A retired cell's leader steps down here — the
        controller normally relinquished it pre-commit, so this is the
        backstop for an incarnation that raced the transition — and its
        drained queue surfaces through the ordinary handoff path."""
        active = set(self.fabric.shard_map.active_shards())
        for s in sorted(active - set(self._coords)):
            self._coords[s] = self._make_coord(s)
        for s in sorted(set(self._coords) - active):
            coord = self._coords[s]
            if coord.leading:
                coord.step_down()
            del self._coords[s]

    def relinquish(
        self, shard: int, event: Optional[str] = None, detail: str = ""
    ) -> bool:
        """Voluntarily surrender a shard mid-topology-transition (called
        by the split/merge transaction on the donor BEFORE the commit):
        the coordinator steps down — the stream drains its pipeline
        through the revoked fence, the queue surfaces with arrival
        stamps/retry budgets intact — and each surfaced pod's timeline
        gets the transition bracket (``shard_split``/``shard_merge``)
        so the gap-free-timeline validator can demand the re-home's
        ``resubmit``/``enqueue`` bridge on the other side."""
        coord = self._coords.get(int(shard))
        if coord is None or not coord.leading:
            return False
        coord.step_down()
        hand = self._handoffs.get(int(shard))
        if hand is not None and self.lifecycle is not None and event:
            for pod, _arr, _tries in hand.queued:
                self.lifecycle.event(
                    pod.meta.uid, event, shard=int(shard), detail=detail
                )
        return True

    def _factory(self, shard: int):
        def build():
            rt = self._build_runtime(shard)
            # 3-tuple: recovery replays through the SAME journal
            # instance the runtime appends to (fresh view over the
            # shared store); pipeline None — the stream drains its own
            return rt.sched, None, rt.sched.bind_journal

        return build

    def _gate(self, shard: int):
        def designated() -> bool:
            # a shard inside an OPEN topology transition is not
            # electable: the donor relinquished it for the re-home, and
            # seating a new owner mid-transaction would let two
            # incarnations serve overlapping ranges (elastic-topology
            # PR; a rollback closes the transition and re-opens the
            # parent's election, a commit retires the cell entirely)
            open_tx = self.fabric.topology.open_transition()
            if open_tx is not None and shard in transition_shards(open_tx):
                return False
            alive = set(self.fabric.membership.alive())
            alive.add(self.name)
            return (
                preferred_candidate(alive, f"shard-{shard}") == self.name
            )

        return designated

    def _pod_filter(self, shard: int):
        flt = self.fabric.shard_map.node_filter(shard)

        def owned(pod) -> bool:
            return bool(pod.spec.node_name) and flt(pod.spec.node_name)

        return owned

    def _teardown(self, shard: int):
        def on_loss(_drained) -> None:
            rt = self._runtimes.pop(shard, None)
            if rt is None:
                return
            handoff = self._handoffs.setdefault(shard, ShardHandoff(shard))
            # the stream drains its pipeline through the revoked fence
            # (speculation discarded, trailing commit rejected with
            # STALE_LEADER_EPOCH) and requeues without burning retries
            handoff.decided.extend(rt.stream.drain_for_handoff())
            handoff.queued.extend(rt.stream.extract_queued())
            rt.stream.close()
            # only THIS shard's informers die; the incarnation's other
            # shards keep serving
            self.hub.detach(rt.informers)
            self.stats["handoffs"] += 1
            # open the handoff seam on the shared log: the takeover side
            # (_note_takeover, possibly on ANOTHER incarnation) closes it
            with self.fabric.handoff_lock:
                self.fabric.handoff_log.append(
                    {
                        "shard": shard,
                        "t_out": rt.sched.extender.tracer.clock(),
                        "t_in": None,
                        "from": self.name,
                        "to": "",
                    }
                )

        return on_loss

    def _build_runtime(self, shard: int) -> ShardRuntime:
        from ..core.snapshot import ClusterSnapshot
        from ..obs.flightrecorder import FlightRecorder

        flt = self.fabric.shard_map.node_filter(shard)
        snap = ClusterSnapshot()
        journal = BindJournal(
            self.fabric.journal_stores[shard], chaos=self.chaos, shard=shard
        )
        sched = self.make_scheduler(
            shard=shard,
            snapshot=snap,
            fence=self.fabric.fences[shard],
            journal=journal,
        )
        # crash-surviving flight recorder: the per-cycle black box lives
        # over the FABRIC's per-shard store (beside the journal), so
        # building a runtime here ADOPTS whatever tail the shard's dead
        # previous owner left — /debug/flightrecorder on the takeover
        # serves the last-N cycles of the incarnation that crashed
        sched.attach_flight_recorder(
            FlightRecorder(
                self.fabric.flight_stores[shard],
                capacity=self.flight_capacity,
                shard=shard,
                incarnation=self.name,
                clock=self.clock,
            )
        )
        # ClaimTable tombstone GC rides the shard journal's run-loop
        # compaction beat (PR 6 queued follow-on): compact settled
        # tombstones past the retention window, then publish the live
        # count so growth is observable (claim_tombstones_live)
        def _gc_claims(_sched=sched):
            live = self.fabric.claims.gc_tombstones(
                self.claim_tombstone_retention_s, now=self.clock()
            )
            _sched.extender.registry.get("claim_tombstones_live").set(
                float(live)
            )

        sched.on_journal_compacted = _gc_claims
        # decision observatory (decision-observatory PR): the per-shard
        # DecisionLedger lives over the FABRIC's store beside the
        # journal and the flight recorder, so a takeover adopts the
        # dead owner's decision tail too; attached BEFORE the stream is
        # built so the pipeline's depth controller records from feed 1
        if self.decisions_enabled:
            from ..obs.decisions import DecisionLedger

            self.fabric.ensure_shard(shard)
            sched.attach_decision_ledger(
                DecisionLedger(
                    self.fabric.decision_stores[shard],
                    capacity=self.decision_capacity,
                    shard=shard,
                    incarnation=self.name,
                    clock=self.clock,
                )
            )
        # overload control (overload-control PR): the fleet-shared
        # brownout ladder gates this runtime's pipeline/bucket, journals
        # into its flight recorder, and shows on its /healthz; the
        # admission controller binds metrics to the first runtime's
        # registry (the fleet scrape merges it once)
        if self.brownout is not None:
            sched.brownout = self.brownout
            sched.extender.services.brownout = self.brownout
            self.brownout.bind_registry(sched.extender.registry)
            self.brownout.attach_health(sched.extender.health)
            if sched.decision_ledger is not None:
                self.brownout.attach_decisions(sched.decision_ledger)
            self.brownout.attach_flight(sched.flight_recorder)
        informers = self.hub.wire_scheduler(sched, node_filter=flt)
        self.hub.start()
        stream_cls = self._stream_cls()
        stream = stream_cls(
            sched,
            max_batch=self.max_batch,
            max_retries=self.max_retries,
            pipelined=self.pipelined,
            pipeline_depth=self.pipeline_depth,
            feed_gate=lambda pod, _s=shard: self._claim(_s, pod),
            lifecycle=self.lifecycle,
            slo=self.slo,
            shard=shard,
            overload=self.overload,
        )
        rt = ShardRuntime(
            shard=shard,
            sched=sched,
            stream=stream,
            informers=informers,
            node_filter=flt,
        )
        self._runtimes[shard] = rt
        return rt

    @staticmethod
    def _stream_cls():
        from ..scheduler.stream import StreamScheduler

        return StreamScheduler

    def _claim(self, shard: int, pod) -> bool:
        """Single-winner claim at feed time, stamped with OUR held epoch
        for the shard. Returns False ONLY when another shard genuinely
        won the pod's claim (safe to drop — the winner schedules it).
        A deposed owner's stamp raises :class:`StaleEpochError` instead,
        which the stream's batch collection treats as "keep the pod
        queued for the handoff": nobody else holds an unclaimed pod, so
        dropping it here would lose it forever."""
        rt = self._runtimes.get(shard)
        if rt is None:
            raise StaleEpochError(-1, 0, what="claim epoch")
        won = self.fabric.claims.claim(
            pod.meta.uid, shard, rt.sched._fence_epoch
        )
        if not won:
            self.stats["claims_lost"] += 1
        if self.lifecycle is not None:
            self.lifecycle.event(
                pod.meta.uid,
                "claim" if won else "claim_lost",
                shard=shard,
            )
        return won

    # ---- public surface ----

    def owned(self) -> List[int]:
        return sorted(
            s for s, c in self._coords.items() if c.leading
        )

    def owns(self, shard: int) -> bool:
        coord = self._coords.get(shard)
        return coord is not None and coord.leading

    def runtime(self, shard: int) -> Optional[ShardRuntime]:
        return self._runtimes.get(shard)

    def last_recovery(self, shard: int):
        coord = self._coords.get(shard)
        return coord.last_recovery if coord is not None else None

    def backlog(self, shard: int) -> int:
        rt = self._runtimes.get(shard)
        return rt.stream.backlog() if rt is not None else 0

    def fleet(self):
        """The incarnation's fleet-aggregation surface (one ``/metrics``
        scrape with a ``shard`` label, merged Chrome trace, per-shard
        ownership/epoch ``/healthz`` rows, ``/slo``,
        ``/debug/flightrecorder``). Read-only over live ownership —
        build on demand, never cached."""
        from ..obs.fleet import FleetServices

        return FleetServices(self)

    def tick(self) -> Dict[int, ShardHandoff]:
        """One election step across every shard: heartbeat, renew owned
        leases, voluntarily hand off shards whose rendezvous-designated
        owner is someone else alive, contend (gated) for free shards.
        Returns the handoffs surfaced this tick — their queued pods are
        the router's to re-place."""
        if self.dead:
            return {}
        self.fabric.membership.heartbeat(self.name)
        self._sync_topology()
        for s, coord in list(self._coords.items()):
            if coord.leading and not self._gate(s)():
                # rebalance: a preferred live candidate exists (e.g. a
                # restarted incarnation rejoined) — voluntary handoff
                coord.step_down()
                continue
            was = coord.leading
            coord.tick()
            if coord.leading and not was:
                self.stats["takeovers"] += 1
                self._note_takeover(s, coord)
        out, self._handoffs = self._handoffs, {}
        return out

    @property
    def handoff_log(self) -> List[dict]:
        """The FLEET's seam-matched handoff instants (shared on the
        fabric): the flow-arrow feed for the merged Chrome trace.
        A snapshot — another incarnation may be appending a seam while
        a /trace render iterates, and a deque refuses that mix."""
        with self.fabric.handoff_lock:
            return [dict(e) for e in self.fabric.handoff_log]

    def _note_takeover(self, shard: int, coord) -> None:
        """Observability bookkeeping for a takeover that just recovered:
        one time-to-recover SLO sample, and the takeover instant closing
        the shard's OPEN handoff seam on the fabric's shared log (the
        donor's ``t_out`` was logged — possibly by another incarnation —
        at drain time; a crash takeover has no drained seam to close and
        logs a point entry instead).

        The shard's FIRST-ever grant (fence epoch 1) is a cold start,
        not a takeover: no handoff entry (nothing was handed off — the
        startup fleet would otherwise render one spurious arrow per
        shard) and no ``recovery`` SLO sample (the cold statehub sync is
        the slowest recovery there is; sampling it would burn the
        failover error budget before any failover happened)."""
        rt = self._runtimes.get(shard)
        now = (
            rt.sched.extender.tracer.clock()
            if rt is not None
            else self.clock()
        )
        with self.fabric.handoff_lock:
            for entry in reversed(self.fabric.handoff_log):
                if entry["shard"] == shard and entry["t_in"] is None:
                    entry["t_in"] = max(now, entry["t_out"])
                    entry["to"] = self.name
                    break
            else:
                if self.fabric.fences[shard].current() <= 1:
                    return  # cold start: not a takeover
                self.fabric.handoff_log.append(
                    {
                        "shard": shard,
                        "t_out": now,
                        "t_in": now,
                        "from": "",
                        "to": self.name,
                    }
                )
        rec = coord.last_recovery
        if self.slo is not None and rec is not None:
            self.slo.observe_recovery(shard, rec.duration_s)

    def submit(self, shard: int, pod, now: Optional[float] = None) -> bool:
        rt = self._runtimes.get(shard)
        if rt is None or not self._coords[shard].leading:
            return False
        rt.stream.submit(pod, now=now)
        return True

    def resubmit(
        self, shard: int, pod, arrival: float, tries: int
    ) -> bool:
        """Handoff path: enqueue with the original arrival stamp/retry
        budget from the donor's queue."""
        rt = self._runtimes.get(shard)
        if rt is None or not self._coords[shard].leading:
            return False
        rt.stream.resubmit(pod, arrival, tries)
        return True

    def pump(self) -> List[Tuple[int, object, Optional[str], float]]:
        """One pump over every owned shard (deterministic shard order).
        Returns ``(shard, pod, node|None, latency)`` decisions.

        Decided pods' claims are deliberately NOT released here: a
        fanned-out pod may still sit in another shard's queue, and a
        released claim would let that stale copy re-claim and
        double-schedule it. The driver releases at pod deletion (the
        apiserver GC analog) — and even then the ClaimTable keeps a
        TOMBSTONE, because a backlogged queue can hold a copy past the
        pod's GC; a post-release claim loses, so the copy is dropped."""
        decided: List[Tuple[int, object, Optional[str], float]] = []
        for s in sorted(self._runtimes):
            rt = self._runtimes[s]
            for pod, node, lat in rt.stream.pump():
                decided.append((s, pod, node, lat))
        return decided

    def flush(self) -> List[Tuple[int, object, Optional[str], float]]:
        decided: List[Tuple[int, object, Optional[str], float]] = []
        for s in sorted(self._runtimes):
            rt = self._runtimes[s]
            for pod, node, lat in rt.stream.flush():
                decided.append((s, pod, node, lat))
        return decided

    def kill(self) -> List[Tuple[int, object]]:
        """Simulated process death: every runtime's state dies WITHOUT a
        drain (no handoff — that is the point), informers are detached
        (the watches died with the process), leases are left to lapse.
        Returns ``(shard, pod)`` for every pod that was queued in the
        dead pumps — the driver reconciles them against the journals
        once the shards' new owners recover."""
        orphans: List[Tuple[int, object]] = []
        for s, rt in sorted(self._runtimes.items()):
            # event=None: a killed queue is NOT a graceful drain — the
            # timeline records orphan (below), never a handoff
            for pod, _arr, _tries in rt.stream.extract_queued(event=None):
                orphans.append((s, pod))
                if self.lifecycle is not None:
                    # the owner died with the pod queued: the timeline
                    # must bracket the dead incarnation (a later
                    # resubmit/enqueue on the new owner bridges it)
                    self.lifecycle.event(
                        pod.meta.uid, "orphan", shard=s, detail=self.name
                    )
            rt.stream.close()
            self.hub.detach(rt.informers)
            coord = self._coords.get(s)
            if coord is not None:
                coord.leading = False
                coord.sched = None
                coord.pipeline = None
        self._runtimes.clear()
        self._handoffs.clear()
        self.dead = True
        self.fabric.membership.forget(self.name)
        return orphans

    def close(self) -> Dict[int, ShardHandoff]:
        """Graceful shutdown: step down from every owned shard (lease
        RELEASED — successors take over immediately instead of waiting
        out the TTL the way a crash forces), leave the membership, and
        tear everything down. Returns the final handoffs — their queued
        pods are the router's to re-place — so a graceful close never
        strands work the way :meth:`kill` deliberately does."""
        for s, coord in sorted(self._coords.items()):
            if coord.leading:
                coord.step_down()  # releases the lease; on_loss drains
        for rt in self._runtimes.values():
            rt.stream.close()
            self.hub.detach(rt.informers)
        self._runtimes.clear()
        self.fabric.membership.forget(self.name)
        self.dead = True
        out, self._handoffs = self._handoffs, {}
        return out
