"""Deterministic warm-standby recovery (HA failover PR tentpole).

A takeover or crash restart rebuilds the scheduler's world in three
ordered steps, each idempotent:

1. **statehub resync** — the informers re-list; bound pods (spec.nodeName
   set) re-charge the snapshot as confirmed assumes through the normal
   ``_pod_upsert`` path. This recovers everything the control plane
   already observed.
2. **journal replay** — the write-ahead bind journal's live set
   (acknowledged binds minus forgets; crash-mid-commit intents are void)
   is reconciled against the snapshot: entries the resync already
   restored are merely re-confirmed; **assumed-but-unbound** entries —
   acknowledged by the journal but never observed as bound by the
   statehub — are re-installed bit-exactly via
   :meth:`~..core.snapshot.ClusterSnapshot.restore_assumed`, and quota
   chains are re-charged from the journaled leaf names.
3. **device re-lower** — the resident NodeState refreshes through the
   existing dirty-row scatter path (a warm standby whose resident tables
   survived pays only the touched rows; a cold restart pays one full
   lower), then is asserted **bit-exact** against a from-scratch host
   lowering — the recovery-correctness contract the chaos soak also
   checks after every takeover.

The recovering scheduler is granted its fencing epoch only after all
three steps succeed, so a half-recovered instance can never commit.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Dict, Optional


@dataclasses.dataclass
class RecoveryReport:
    """What a takeover rebuilt, for the operator and the soak asserts."""

    epoch: int = 0
    synced: bool = True
    #: journal entries re-installed via restore_assumed (not covered by
    #: the statehub resync — the assumed-but-unbound window)
    replayed: int = 0
    #: journal entries the resync had already restored (bound pods)
    reconfirmed: int = 0
    #: entries whose node the resynced world no longer knows
    skipped_missing_node: int = 0
    #: quota chains re-charged (journal leaves + re-listed bound pods)
    quota_charges: int = 0
    open_intents: int = 0
    #: state-integrity PR: the replay fast-forwarded from a verified
    #: checkpoint recovery image (bounded RTO) / fell back to the full
    #: history walk (image digest mismatch or the
    #: ``checkpoint.digest_mismatch`` chaos point)
    used_checkpoint: bool = False
    checkpoint_fallback: bool = False
    #: journal records actually APPLIED by the replay (the RTO-bearing
    #: count the recovery bench sweeps over journal length)
    replay_applied: int = 0
    #: corrupt journal records the store quarantined (acked state behind
    #: them survived — the zero-lost-ack contract under media faults)
    journal_corrupt_records: int = 0
    #: bit-exact fingerprint of the re-lowered resident node table (the
    #: same digest the anti-entropy scrubber computes per window)
    resident_digest: str = ""
    warm_lower_s: float = 0.0
    #: wall time of the whole recovery sequence (resync + replay +
    #: re-lower) — the time-to-recover SLI the SLO layer samples
    duration_s: float = 0.0
    bitexact: Optional[bool] = None
    #: uid -> node for every acknowledged binding the journal preserved —
    #: the control plane reconciles its pending queue against this
    bindings: Dict[str, str] = dataclasses.field(default_factory=dict)


def assert_resident_bitexact(sched) -> None:
    """The device-resident NodeState must be BIT-EXACT against a
    from-scratch host lowering (the cold full re-lower is a pure
    function of the host arrays, so equality against the host arrays IS
    equality against a cold re-lower). Any missed dirty mark across the
    recovery path shows up here as a stale row."""
    import numpy as np

    snap = sched.snapshot
    na = snap.nodes
    ns = sched.node_state()  # refreshes the resident state (dirty scatter)
    est = np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
    sched_rows = na.schedulable
    if (
        sched.args.filter_expired_node_metrics
        and not sched.args.enable_schedule_when_node_metrics_expired
    ):
        sched_rows = sched_rows & (na.metric_fresh | ~na.has_metric)
    for got, want in (
        (ns.allocatable, na.allocatable),
        (ns.requested, na.requested),
        (ns.estimated_used, est),
        (ns.prod_used, na.prod_usage + na.assigned_pending_prod),
        (ns.metric_fresh, na.metric_fresh),
        (ns.schedulable, sched_rows),
    ):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def _restore_exact_holds(sched, uid: str, node: str, entry: dict) -> None:
    """Re-install journaled NUMA zone / device-slot holds (PR 6
    satellite). Idempotent via the managers' restore_hold guards; the
    journaled indices are authoritative — a fresh allocate() could
    legally pick DIFFERENT slots than the dead leader did, diverging
    from the annotations the kubelet already acted on."""
    numa_hold = entry.get("numa")
    if numa_hold and sched.numa is not None:
        sched.numa.restore_hold(uid, node, numa_hold)
    dev_hold = entry.get("dev")
    if dev_hold and sched.devices is not None:
        sched.devices.restore_hold(uid, node, dev_hold)


def recover_scheduler(
    sched,
    journal,
    hub=None,
    epoch: Optional[int] = None,
    verify: bool = True,
    sync_timeout_s: float = 10.0,
    rebuild_quotas: bool = True,
    pod_filter=None,
) -> RecoveryReport:
    """Run the recovery sequence on ``sched`` and (optionally) grant it
    leadership epoch ``epoch`` once the world is provably rebuilt.

    ``journal`` is the :class:`~..core.journal.BindJournal` the previous
    leader wrote (its store survived the process); ``hub`` the shared
    :class:`~.statehub.ClusterStateHub` whose informers must re-sync
    first. ``verify=True`` asserts resident-state bit-exactness against
    a cold re-lower before leadership is granted. ``pod_filter`` scopes
    the quota rebuild to this scheduler's partition (horizontally
    partitioned control plane: a shard owner must not charge its quota
    ledger for pods bound on foreign shards' nodes — those shards'
    owners rebuild them from their own journals).
    """
    import numpy as np

    from ..core.snapshot import _AssumedPod
    from ..obs.errors import report_exception

    health = sched.extender.health
    reg = sched.extender.registry
    rep = RecoveryReport(epoch=epoch if epoch is not None else 0)
    health.set("recovery", False, "recovery in progress")
    t0 = _time.perf_counter()
    # fleet-tracing PR: replayed bindings re-enter the lifecycle tracker
    # as ``recover`` events, seeded from the journaled compact context
    # ("lc": original submit stamp + hop count) so a pod that crossed
    # the dead incarnation keeps ONE timeline with its TRUE arrival
    lifecycle = getattr(sched, "lifecycle", None)
    lc_shard = journal.shard if journal.shard is not None else -1
    if hub is not None:
        rep.synced = hub.wait_synced(sync_timeout_s)
    # state-integrity PR: prefer checkpoint + tail-replay (RTO bounded by
    # live set + tail, not journal length); any image-digest mismatch —
    # including the ``checkpoint.digest_mismatch`` chaos point's forced
    # verdict — falls back to the full-history walk
    replay = journal.replay()
    if replay.used_checkpoint and (
        replay.checkpoint_fallbacks > 0
        or sched.chaos.fire("checkpoint.digest_mismatch")
    ):
        replay = journal.replay(use_checkpoint=False)
        rep.checkpoint_fallback = True
        reg.get("recovery_checkpoint_fallback_total").inc()
    elif not replay.used_checkpoint and replay.checkpoint_fallbacks > 0:
        # every image in the store was rejected: the full walk already
        # ran, but the fallback is an operator-visible event
        rep.checkpoint_fallback = True
        reg.get("recovery_checkpoint_fallback_total").inc()
    rep.used_checkpoint = replay.used_checkpoint
    rep.replay_applied = replay.applied
    rep.journal_corrupt_records = replay.corrupt_records
    rep.open_intents = replay.open_intents
    snap = sched.snapshot
    with snap.lock:
        if rebuild_quotas and sched.quotas is not None and hub is not None:
            # durable quota charges died with the old process; rebuild
            # them from the re-listed bound pods (the journal-replayed
            # unbound entries are charged from their journaled leaf
            # below). reset first so a repeated recovery is idempotent.
            sched.quotas.reset_usage()
            from ..scheduler.plugins.elasticquota import quota_name_of

            pods, _rv = hub.pods.list()
            for pod in pods.values():
                if not pod.spec.node_name:
                    continue
                if pod_filter is not None and not pod_filter(pod):
                    continue
                leaf = quota_name_of(pod)
                if leaf is not None and sched.quotas.index_of(leaf) is not None:
                    sched.quotas.assign_pod(leaf, pod)
                    rep.quota_charges += 1
        for uid, entry in replay.live.items():
            node = entry.get("node", "")
            rep.bindings[uid] = node
            if snap.is_assumed(uid):
                # statehub resync already restored the charge (bound pod
                # observed); the journal merely confirms it — but the
                # exact NUMA zone / device-slot holds are NOT part of
                # the resync (the informer path only re-charges node
                # capacity), so re-install them from the journal too
                snap.confirm_pod(uid)
                sched._bound_nodes.setdefault(uid, node)
                _restore_exact_holds(sched, uid, node, entry)
                if lifecycle is not None:
                    lifecycle.recovered(
                        uid, lc_shard, node, ctx=entry.get("lc")
                    )
                rep.reconfirmed += 1
                continue
            idx = snap.node_id(node)
            if idx is None:
                # the resynced world no longer has the node — the binding
                # is moot (its pod either moved or died with the node)
                rep.skipped_missing_node += 1
                continue
            snap.restore_assumed(
                uid,
                _AssumedPod(
                    node_idx=idx,
                    request=np.asarray(entry["req"], np.float32),
                    estimate=np.asarray(entry["est"], np.float32),
                    is_prod=bool(entry.get("prod", False)),
                    assume_time=_time.time(),
                    absorbed=False,
                    confirmed=bool(entry.get("conf", True)),
                    bind_nominal_cpu=float(entry.get("nom", 0.0)),
                ),
            )
            sched._bound_nodes[uid] = node
            _restore_exact_holds(sched, uid, node, entry)
            if lifecycle is not None:
                lifecycle.recovered(
                    uid, lc_shard, node, ctx=entry.get("lc")
                )
            leaf = entry.get("quota")
            if (
                rebuild_quotas
                and leaf
                and sched.quotas is not None
                and sched.quotas.index_of(leaf) is not None
            ):
                # re-charge the chain from the journaled request row;
                # the per-pod victim record rebuilds when the pod is
                # re-observed through the informer
                sched.quotas.charge(
                    leaf, {}, vec=np.asarray(entry["req"], np.float32)
                )
                rep.quota_charges += 1
            rep.replayed += 1
        if rep.replayed:
            reg.get("recovery_replayed_total").inc(rep.replayed)
        # warm re-lower: the dirty-row scatter path picks up exactly the
        # rows the replay touched (full lower only when this process has
        # no resident state yet — the cold-restart case)
        t_low = _time.perf_counter()
        try:
            ns = sched.node_state()
            import jax as _jax

            # fence the async dispatch: the re-lower time must cover the
            # actual transfer/scatter, not just its enqueue
            _jax.block_until_ready(
                [ns.allocatable, ns.requested, ns.estimated_used]
            )
            rep.warm_lower_s = _time.perf_counter() - t_low
            from ..core.integrity import array_digest

            rep.resident_digest = array_digest(
                [
                    ns.allocatable,
                    ns.requested,
                    ns.estimated_used,
                    ns.prod_used,
                ]
            )
            if verify:
                assert_resident_bitexact(sched)
                rep.bitexact = True
        except AssertionError:
            rep.bitexact = False
            health.set(
                "recovery",
                False,
                "resident state diverged from cold re-lower after replay",
            )
            raise
        except Exception as exc:  # noqa: BLE001 — surfaced, not fatal:
            # no device available (host-reference deployments) — the
            # host arrays are already correct; resident state lowers
            # lazily on the first real cycle
            report_exception("recovery.relower", exc, registry=reg)
    if rep.journal_corrupt_records or replay.seq_gaps:
        # the recovery replayed THROUGH the quarantined corruption and
        # the world verified — re-promote journal_integrity (the
        # counters and the quarantine sidecar keep the evidence)
        journal.mark_integrity_recovered()
    if epoch is not None:
        sched.grant_leadership(epoch)
        rep.epoch = epoch
    elif replay.epoch_high > sched._fence_epoch:
        # no election wired (epoch=None — e.g. the CLI restart path):
        # continue under the journal's last known epoch, else every
        # subsequent append from this writer would be refused as stale
        # and the scheduler could never commit again
        sched._fence_epoch = replay.epoch_high
        rep.epoch = replay.epoch_high
    rep.duration_s = _time.perf_counter() - t0
    health.set(
        "recovery",
        True,
        f"recovered in {rep.duration_s * 1e3:.1f}ms: "
        f"replayed={rep.replayed} reconfirmed={rep.reconfirmed} "
        f"skipped={rep.skipped_missing_node} "
        f"open_intents={rep.open_intents}",
    )
    return rep
