// Node telemetry collector (native).
//
// Rebuild of the reference's single native component — the cgo binding to
// libpfm4 for perf-based CPI collection
// (pkg/koordlet/util/perf_group/perf_group_linux.go:39-43) plus the PSI /
// procfs readers of the performance collector
// (pkg/koordlet/metricsadvisor/collectors/performance). perf_event_open is
// unavailable in unprivileged containers, so the hot sources here are the
// procfs surfaces every collector tick reads: /proc/stat (cpu jiffies),
// /proc/meminfo, and /proc/pressure/{cpu,memory,io} (PSI). Parsing them in
// C++ keeps the per-tick cost flat as tick rates rise (the reference runs
// 12 collectors on 1s-5s timers) and is exposed to Python over ctypes.
//
// Build: make -C koordinator_tpu/runtime (produces libkoordtelemetry.so).

#include <cstdio>
#include <cstring>
#include <cstdlib>

extern "C" {

typedef struct {
  double user, nice_, system_, idle, iowait, irq, softirq, steal;
} koord_cpu_times;

// Reads the aggregate "cpu " line of /proc/stat in USER_HZ jiffies.
// Returns 0 on success.
int koord_read_cpu_times(koord_cpu_times* out) {
  FILE* f = std::fopen("/proc/stat", "r");
  if (!f) return -1;
  char line[512];
  int rc = -1;
  while (std::fgets(line, sizeof(line), f)) {
    if (std::strncmp(line, "cpu ", 4) == 0) {
      unsigned long long v[8] = {0};
      int n = std::sscanf(line + 4,
                          "%llu %llu %llu %llu %llu %llu %llu %llu",
                          &v[0], &v[1], &v[2], &v[3], &v[4], &v[5], &v[6],
                          &v[7]);
      if (n >= 4) {
        out->user = (double)v[0];
        out->nice_ = (double)v[1];
        out->system_ = (double)v[2];
        out->idle = (double)v[3];
        out->iowait = (double)v[4];
        out->irq = (double)v[5];
        out->softirq = (double)v[6];
        out->steal = (double)v[7];
        rc = 0;
      }
      break;
    }
  }
  std::fclose(f);
  return rc;
}

// MemTotal / MemAvailable in KiB. Returns 0 on success.
int koord_read_meminfo(double* total_kib, double* available_kib) {
  FILE* f = std::fopen("/proc/meminfo", "r");
  if (!f) return -1;
  char line[256];
  int found = 0;
  *total_kib = 0;
  *available_kib = 0;
  while (std::fgets(line, sizeof(line), f) && found < 2) {
    unsigned long long kb;
    if (std::sscanf(line, "MemTotal: %llu kB", &kb) == 1) {
      *total_kib = (double)kb;
      found++;
    } else if (std::sscanf(line, "MemAvailable: %llu kB", &kb) == 1) {
      *available_kib = (double)kb;
      found++;
    }
  }
  std::fclose(f);
  return found == 2 ? 0 : -1;
}

// PSI avg10 for "cpu", "memory" or "io". full_avg10 is 0 for cpu (the
// kernel reports no full line for cpu before 5.13). Returns 0 on success.
int koord_read_psi(const char* resource, double* some_avg10,
                   double* full_avg10) {
  char path[64];
  std::snprintf(path, sizeof(path), "/proc/pressure/%s", resource);
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  char line[256];
  *some_avg10 = 0;
  *full_avg10 = 0;
  int rc = -1;
  while (std::fgets(line, sizeof(line), f)) {
    double avg10;
    if (std::sscanf(line, "some avg10=%lf", &avg10) == 1) {
      *some_avg10 = avg10;
      rc = 0;
    } else if (std::sscanf(line, "full avg10=%lf", &avg10) == 1) {
      *full_avg10 = avg10;
    }
  }
  std::fclose(f);
  return rc;
}

// Per-cgroup cpu usage from cpuacct (v1) or cpu.stat (v2), nanoseconds.
// root: cgroupfs mount, group: relative dir. Returns 0 on success.
int koord_read_cgroup_cpu_ns(const char* root, const char* group,
                             double* usage_ns) {
  char path[512];
  std::snprintf(path, sizeof(path), "%s/%s/cpuacct.usage", root, group);
  FILE* f = std::fopen(path, "r");
  if (f) {
    unsigned long long ns = 0;
    int ok = std::fscanf(f, "%llu", &ns) == 1;
    std::fclose(f);
    if (ok) {
      *usage_ns = (double)ns;
      return 0;
    }
  }
  std::snprintf(path, sizeof(path), "%s/%s/cpu.stat", root, group);
  f = std::fopen(path, "r");
  if (!f) return -1;
  char line[256];
  int rc = -1;
  while (std::fgets(line, sizeof(line), f)) {
    unsigned long long usec;
    if (std::sscanf(line, "usage_usec %llu", &usec) == 1) {
      *usage_ns = (double)usec * 1000.0;
      rc = 0;
      break;
    }
  }
  std::fclose(f);
  return rc;
}

}  // extern "C"
