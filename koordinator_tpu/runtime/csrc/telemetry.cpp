// Node telemetry collector (native).
//
// Rebuild of the reference's single native component — the cgo binding to
// libpfm4 for perf-based CPI collection
// (pkg/koordlet/util/perf_group/perf_group_linux.go:39-43) plus the PSI /
// procfs readers of the performance collector
// (pkg/koordlet/metricsadvisor/collectors/performance). perf_event_open is
// unavailable in unprivileged containers, so the hot sources here are the
// procfs surfaces every collector tick reads: /proc/stat (cpu jiffies),
// /proc/meminfo, and /proc/pressure/{cpu,memory,io} (PSI). Parsing them in
// C++ keeps the per-tick cost flat as tick rates rise (the reference runs
// 12 collectors on 1s-5s timers) and is exposed to Python over ctypes.
//
// Build: make -C koordinator_tpu/runtime (produces libkoordtelemetry.so).

#include <cstdio>
#include <cstring>
#include <cstdlib>

#if defined(__linux__)
#include <linux/perf_event.h>
#include <sys/ioctl.h>
#include <sys/syscall.h>
#include <unistd.h>
#endif

extern "C" {

typedef struct {
  double user, nice_, system_, idle, iowait, irq, softirq, steal;
} koord_cpu_times;

// Reads the aggregate "cpu " line of /proc/stat in USER_HZ jiffies.
// Returns 0 on success.
int koord_read_cpu_times(koord_cpu_times* out) {
  FILE* f = std::fopen("/proc/stat", "r");
  if (!f) return -1;
  char line[512];
  int rc = -1;
  while (std::fgets(line, sizeof(line), f)) {
    if (std::strncmp(line, "cpu ", 4) == 0) {
      unsigned long long v[8] = {0};
      int n = std::sscanf(line + 4,
                          "%llu %llu %llu %llu %llu %llu %llu %llu",
                          &v[0], &v[1], &v[2], &v[3], &v[4], &v[5], &v[6],
                          &v[7]);
      if (n >= 4) {
        out->user = (double)v[0];
        out->nice_ = (double)v[1];
        out->system_ = (double)v[2];
        out->idle = (double)v[3];
        out->iowait = (double)v[4];
        out->irq = (double)v[5];
        out->softirq = (double)v[6];
        out->steal = (double)v[7];
        rc = 0;
      }
      break;
    }
  }
  std::fclose(f);
  return rc;
}

// MemTotal / MemAvailable in KiB. Returns 0 on success.
int koord_read_meminfo(double* total_kib, double* available_kib) {
  FILE* f = std::fopen("/proc/meminfo", "r");
  if (!f) return -1;
  char line[256];
  int found = 0;
  *total_kib = 0;
  *available_kib = 0;
  while (std::fgets(line, sizeof(line), f) && found < 2) {
    unsigned long long kb;
    if (std::sscanf(line, "MemTotal: %llu kB", &kb) == 1) {
      *total_kib = (double)kb;
      found++;
    } else if (std::sscanf(line, "MemAvailable: %llu kB", &kb) == 1) {
      *available_kib = (double)kb;
      found++;
    }
  }
  std::fclose(f);
  return found == 2 ? 0 : -1;
}

// PSI avg10 for "cpu", "memory" or "io". full_avg10 is 0 for cpu (the
// kernel reports no full line for cpu before 5.13). Returns 0 on success.
int koord_read_psi(const char* resource, double* some_avg10,
                   double* full_avg10) {
  char path[64];
  std::snprintf(path, sizeof(path), "/proc/pressure/%s", resource);
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  char line[256];
  *some_avg10 = 0;
  *full_avg10 = 0;
  int rc = -1;
  while (std::fgets(line, sizeof(line), f)) {
    double avg10;
    if (std::sscanf(line, "some avg10=%lf", &avg10) == 1) {
      *some_avg10 = avg10;
      rc = 0;
    } else if (std::sscanf(line, "full avg10=%lf", &avg10) == 1) {
      *full_avg10 = avg10;
    }
  }
  std::fclose(f);
  return rc;
}

// Per-cgroup cpu usage from cpuacct (v1) or cpu.stat (v2), nanoseconds.
// root: cgroupfs mount, group: relative dir. Returns 0 on success.
int koord_read_cgroup_cpu_ns(const char* root, const char* group,
                             double* usage_ns) {
  char path[512];
  std::snprintf(path, sizeof(path), "%s/%s/cpuacct.usage", root, group);
  FILE* f = std::fopen(path, "r");
  if (f) {
    unsigned long long ns = 0;
    int ok = std::fscanf(f, "%llu", &ns) == 1;
    std::fclose(f);
    if (ok) {
      *usage_ns = (double)ns;
      return 0;
    }
  }
  std::snprintf(path, sizeof(path), "%s/%s/cpu.stat", root, group);
  f = std::fopen(path, "r");
  if (!f) return -1;
  char line[256];
  int rc = -1;
  while (std::fgets(line, sizeof(line), f)) {
    unsigned long long usec;
    if (std::sscanf(line, "usage_usec %llu", &usec) == 1) {
      *usage_ns = (double)usec * 1000.0;
      rc = 0;
      break;
    }
  }
  std::fclose(f);
  return rc;
}

// --- CPI via perf_event_open --------------------------------------------
//
// The reference's only cgo component binds libpfm4 to set up
// perf_event_open counter groups for cycles/instructions per cgroup
// (perf_group_linux.go). The two generic hardware events need no event-
// encoding library, so this rebuild calls the syscall directly: one
// counter group (cycles leader + instructions) per CPU-wide session.
// Unprivileged containers typically get EPERM/EACCES — callers must treat
// rc != 0 as "CPI unavailable" (the reference gates the collector behind
// a feature flag for the same reason).

#if defined(__linux__)
// System-wide counting needs one fd pair per online CPU with pid=-1,
// cpu=N (pid=-1 with cpu=-1 is EINVAL); reads are summed across CPUs.
#define KOORD_CPI_MAX_CPUS 512
static int cpi_n_cpus = 0;
static int cpi_fd_cycles[KOORD_CPI_MAX_CPUS];
static int cpi_fd_instr[KOORD_CPI_MAX_CPUS];

void koord_cpi_close(void);

static int perf_open_cpu(unsigned long long config, int cpu, int group_fd) {
  struct perf_event_attr attr;
  std::memset(&attr, 0, sizeof(attr));
  attr.type = PERF_TYPE_HARDWARE;
  attr.size = sizeof(attr);
  attr.config = config;
  attr.disabled = group_fd == -1 ? 1 : 0;
  attr.exclude_hv = 1;
  return (int)syscall(SYS_perf_event_open, &attr, -1 /*all pids*/, cpu,
                      group_fd, 0);
}

// Open a cycles+instructions group on every online CPU. Returns 0 on
// success (requires perf_event_paranoid <= 0 or CAP_PERFMON for
// system-wide counters — unprivileged containers get EPERM/EACCES).
int koord_cpi_open(void) {
  if (cpi_n_cpus > 0) return 0;
  long n = sysconf(_SC_NPROCESSORS_ONLN);
  if (n <= 0) return -1;
  if (n > KOORD_CPI_MAX_CPUS) n = KOORD_CPI_MAX_CPUS;
  for (int cpu = 0; cpu < (int)n; cpu++) {
    int fc = perf_open_cpu(PERF_COUNT_HW_CPU_CYCLES, cpu, -1);
    if (fc < 0) {
      koord_cpi_close();
      return -1;
    }
    int fi = perf_open_cpu(PERF_COUNT_HW_INSTRUCTIONS, cpu, fc);
    if (fi < 0) {
      close(fc);
      koord_cpi_close();
      return -1;
    }
    ioctl(fc, PERF_EVENT_IOC_RESET, PERF_IOC_FLAG_GROUP);
    ioctl(fc, PERF_EVENT_IOC_ENABLE, PERF_IOC_FLAG_GROUP);
    cpi_fd_cycles[cpi_n_cpus] = fc;
    cpi_fd_instr[cpi_n_cpus] = fi;
    cpi_n_cpus++;
  }
  return 0;
}

// Cumulative node-wide cycles/instructions since open. 0 on success.
int koord_cpi_read(double* cycles, double* instructions) {
  if (cpi_n_cpus <= 0) return -1;
  double c_total = 0, i_total = 0;
  for (int k = 0; k < cpi_n_cpus; k++) {
    unsigned long long c = 0, i = 0;
    if (read(cpi_fd_cycles[k], &c, sizeof(c)) != sizeof(c)) return -1;
    if (read(cpi_fd_instr[k], &i, sizeof(i)) != sizeof(i)) return -1;
    c_total += (double)c;
    i_total += (double)i;
  }
  *cycles = c_total;
  *instructions = i_total;
  return 0;
}

void koord_cpi_close(void) {
  for (int k = 0; k < cpi_n_cpus; k++) {
    close(cpi_fd_cycles[k]);
    close(cpi_fd_instr[k]);
  }
  cpi_n_cpus = 0;
}
#else
int koord_cpi_open(void) { return -1; }
int koord_cpi_read(double* cycles, double* instructions) {
  (void)cycles;
  (void)instructions;
  return -1;
}
void koord_cpi_close(void) {}
#endif

// Cached page bytes from /proc/meminfo (pagecache collector). 0 on success.
int koord_read_pagecache_kib(double* cached_kib) {
  FILE* f = std::fopen("/proc/meminfo", "r");
  if (!f) return -1;
  char line[256];
  int rc = -1;
  while (std::fgets(line, sizeof(line), f)) {
    unsigned long long kb;
    if (std::sscanf(line, "Cached: %llu kB", &kb) == 1) {
      *cached_kib = (double)kb;
      rc = 0;
      break;
    }
  }
  std::fclose(f);
  return rc;
}

// CFS throttling counters of a cgroup's cpu.stat (podthrottled collector).
// Returns 0 on success.
int koord_read_cgroup_throttled(const char* root, const char* group,
                                double* nr_periods, double* nr_throttled) {
  char path[512];
  std::snprintf(path, sizeof(path), "%s/%s/cpu.stat", root, group);
  FILE* f = std::fopen(path, "r");
  if (!f) return -1;
  char line[256];
  *nr_periods = 0;
  *nr_throttled = 0;
  int found = 0;
  while (std::fgets(line, sizeof(line), f)) {
    unsigned long long v;
    if (std::sscanf(line, "nr_periods %llu", &v) == 1) {
      *nr_periods = (double)v;
      found++;
    } else if (std::sscanf(line, "nr_throttled %llu", &v) == 1) {
      *nr_throttled = (double)v;
      found++;
    }
  }
  std::fclose(f);
  return found == 2 ? 0 : -1;
}

// True for partition / stacked-device rows that would double-count IO
// already reported by the whole-disk row: sdX1/vdX1/hdX1/xvdX1 (letters
// then trailing digits), nvme0n1p1/mmcblk0p1 (pN suffix), and dm-/md
// virtual devices layered over real disks.
static int koord_diskstats_skip(const char* name) {
  size_t len = std::strlen(name);
  if (len == 0) return 1;
  if (std::strncmp(name, "loop", 4) == 0 || std::strncmp(name, "ram", 3) == 0)
    return 1;
  if (std::strncmp(name, "dm-", 3) == 0 || std::strncmp(name, "md", 2) == 0)
    return 1;
  // pN suffix (nvme/mmcblk partitions)
  size_t i = len;
  while (i > 0 && name[i - 1] >= '0' && name[i - 1] <= '9') i--;
  if (i < len) {
    if (i > 0 && name[i - 1] == 'p' &&
        (std::strncmp(name, "nvme", 4) == 0 ||
         std::strncmp(name, "mmcblk", 6) == 0))
      return 1;
    // letters-then-digits partitions of sd/hd/vd/xvd disks
    if (std::strncmp(name, "sd", 2) == 0 || std::strncmp(name, "hd", 2) == 0 ||
        std::strncmp(name, "vd", 2) == 0 || std::strncmp(name, "xvd", 3) == 0)
      return 1;
  }
  return 0;
}

// Aggregate sectors read/written across /proc/diskstats whole physical
// disks (nodestorageinfo collector). Returns 0 on success.
int koord_read_diskstats(double* sectors_read, double* sectors_written) {
  FILE* f = std::fopen("/proc/diskstats", "r");
  if (!f) return -1;
  char line[512];
  unsigned long long r_total = 0, w_total = 0;
  int rc = -1;
  while (std::fgets(line, sizeof(line), f)) {
    unsigned major, minor;
    char name[64];
    unsigned long long rd_ios, rd_merges, rd_sectors, rd_ticks;
    unsigned long long wr_ios, wr_merges, wr_sectors;
    int n = std::sscanf(line, "%u %u %63s %llu %llu %llu %llu %llu %llu %llu",
                        &major, &minor, name, &rd_ios, &rd_merges,
                        &rd_sectors, &rd_ticks, &wr_ios, &wr_merges,
                        &wr_sectors);
    if (n == 10 && !koord_diskstats_skip(name)) {
      r_total += rd_sectors;
      w_total += wr_sectors;
      rc = 0;
    }
  }
  std::fclose(f);
  *sectors_read = (double)r_total;
  *sectors_written = (double)w_total;
  return rc;
}

}  // extern "C"
