"""Leadership state machine tying election, fencing, journal and
recovery into one coordinator (HA failover PR).

One :class:`LeaderCoordinator` per scheduler instance. ``tick()`` runs a
single election protocol step (testable without threads or wall-clock
sleeps — inject the elector's clock) and drives the transitions:

* **takeover** — the lease is acquired under a fresh epoch; the shared
  :class:`~..core.journal.EpochFence` adopts it (deposing every older
  grant at the commit/channel boundaries), then
  :func:`~.recovery.recover_scheduler` rebuilds the world from the
  statehub resync + journal replay and only THEN grants the scheduler
  its epoch — a half-recovered instance can never commit.
* **loss** — the scheduler revokes its own epoch immediately (local
  sentinel −1: every in-flight commit is fenced regardless of who holds
  the new grant), then the pipeline drains for handoff: speculation
  discarded, trailing commit flushed through the fencing check, state
  surfaced on ``/healthz``.

Named chaos point (ROADMAP rule): ``leader.lost`` — evaluated at the
top of a leader's tick; firing force-releases the lease, so the same
seed yields the same flap schedule.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..chaos import NULL_INJECTOR
from .containment import BootCrashError, BootPlan
from .recovery import RecoveryReport, recover_scheduler


class LeaderCoordinator:
    """Election steps + fenced grant/revoke for one scheduler instance.

    Horizontal partitioning (PR 6) runs ONE coordinator per (incarnation,
    shard) over the shard's own lease/fence/journal; three hooks make
    that composition possible without subclassing:

    * ``sched_factory`` — builds the scheduler lazily on takeover (a
      standby for S shards must not pay S schedulers' worth of resident
      state up front). It returns ``(sched, pipeline)`` or ``(sched,
      pipeline, journal)``; ``pipeline`` may be None, and the 3-tuple
      form supplies the journal recovery replays (required when none
      was passed at construction).
    * ``acquire_gate`` — multi-standby election: evaluated before a
      NON-leader contends for the lease. The sharded election gates each
      candidate on the rendezvous ranking over live members, so a free
      shard is taken by its designated successor instead of whoever
      ticks first (and leadership never thunders). A current leader
      always renews regardless of the gate.
    * ``on_loss(drained)`` — teardown hook after a loss drained the
      pipeline (the sharded runtime detaches its informers and surfaces
      its queue for re-routing).
    * ``recovery_pod_filter`` — forwarded to recover_scheduler so a
      shard owner's quota rebuild only charges pods of its partition.
    """

    def __init__(
        self,
        sched=None,
        elector=None,
        fence=None,
        journal=None,
        hub=None,
        pipeline=None,
        verify_recovery: bool = True,
        chaos=None,
        sched_factory=None,
        acquire_gate=None,
        on_loss=None,
        recovery_pod_filter=None,
        quarantine=None,
        governor=None,
    ):
        if sched is None and sched_factory is None:
            raise ValueError("LeaderCoordinator needs sched or sched_factory")
        self.sched = sched
        self.sched_factory = sched_factory
        self.elector = elector
        self.fence = fence
        self.journal = journal
        self.hub = hub
        self.pipeline = pipeline
        self.verify_recovery = verify_recovery
        self.acquire_gate = acquire_gate
        self.on_loss_cb = on_loss
        self.recovery_pod_filter = recovery_pod_filter
        self.chaos = chaos or getattr(sched, "chaos", None) or NULL_INJECTOR
        #: gray-failure containment: the poison-quarantine ledger this
        #: incarnation adopts BEFORE replaying the journal on takeover —
        #: a successor must reject the blamed pods from cycle one, not
        #: re-crash on the same batch the predecessor died isolating
        self.quarantine = quarantine
        #: crash-loop governor: boot/death records + backoff gate. A
        #: takeover that raises counts as a death; K rapid deaths impose
        #: exponential boot backoff and a DEGRADED next boot.
        self.governor = governor
        #: the governor's plan for the CURRENT boot (None until a
        #: governed takeover succeeds); the embedder applies knobs the
        #: coordinator cannot reach (brownout cap on the stream)
        self.boot_plan: Optional[BootPlan] = None
        self.leading = False
        #: report of the most recent takeover's recovery
        self.last_recovery: Optional[RecoveryReport] = None
        if sched is not None:
            sched.extender.health.set(
                "leader", True, "standby (no grant yet)"
            )

    # ---- transitions ----

    def _on_takeover(self) -> None:
        # chaos: a crash DURING boot/takeover — fires before the fence
        # adopts the epoch, so the failed boot leaves no deposed grant
        # behind (the lease lapses and re-elects, exactly like a factory
        # failure). tick() turns the raise into a governed death record.
        if self.chaos.enabled and self.chaos.fire("scheduler.boot_crash"):
            raise BootCrashError("injected crash during takeover boot")
        epoch = self.elector.current_epoch() or self.fence.advance()
        # the factory runs BEFORE the fence adopts the new epoch: a
        # factory failure then leaves the previous grant un-deposed
        # (the lease lapses and re-elects) instead of fencing the old
        # leader with no recovered successor
        if self.sched_factory is not None:
            built = self.sched_factory()
            if len(built) == 3:
                # (sched, pipeline, journal): the factory supplies the
                # journal recovery replays — necessarily the SAME
                # instance the runtime appends to
                self.sched, self.pipeline, self.journal = built
            else:
                self.sched, self.pipeline = built
        if self.journal is None:
            raise ValueError(
                "LeaderCoordinator has no journal to recover from: pass "
                "journal= at construction or return (sched, pipeline, "
                "journal) from sched_factory"
            )
        # the shared fence mirrors the lease's epoch: adopting it is what
        # deposes every older grant at the commit/channel boundaries
        self.fence.adopt(epoch)
        # QUARANTINE BEFORE REPLAY: the blame ledger is adopted before
        # the journal replays the queue, so a predecessor's poison pods
        # are rejected at this incarnation's cycle gate from the very
        # first cycle — the successor never re-runs the crash that
        # produced the blame
        if self.quarantine is not None:
            self.quarantine.adopt()
            self.sched.quarantine = self.quarantine
        self.last_recovery = recover_scheduler(
            self.sched,
            self.journal,
            hub=self.hub,
            epoch=epoch,
            verify=self.verify_recovery,
            pod_filter=self.recovery_pod_filter,
        )
        if self.governor is not None:
            self.governor.note_boot()
            self.boot_plan = self.governor.boot_plan()
            if self.boot_plan.degraded:
                # DEGRADED boot: shallow pipeline (no deep speculation
                # while crash cause is unknown) and the device ladder
                # pre-demoted one level so the first cycles run the
                # battle-tested paths; the quarantine attach above is
                # what arms bisection from cycle one
                if self.pipeline is not None:
                    self.pipeline.depth = 1
                self.sched._fallback_level = max(
                    self.sched._fallback_level, 1
                )
                self.sched.extender.health.set(
                    "leader",
                    True,
                    "leading (DEGRADED boot: %d rapid deaths)"
                    % self.boot_plan.rapid_deaths,
                )
        self.leading = True

    def _on_loss(self, reason: str):
        self.leading = False
        if self.sched is not None:
            self.sched.revoke_leadership(f"standby ({reason})")
        drained = None
        if self.pipeline is not None:
            drained = self.pipeline.drain_for_handoff()
        if self.on_loss_cb is not None:
            self.on_loss_cb(drained)
        if self.sched_factory is not None:
            # lazy-construction contract: a standby must not retain the
            # lost shard's runtime (snapshot, resident device state) —
            # the next takeover rebuilds it through the factory
            self.sched = None
            self.pipeline = None
        return drained

    # ---- public surface ----

    def tick(self) -> Tuple[bool, Optional[object]]:
        """One election protocol step. Returns ``(is_leader,
        drained_outcome)`` — ``drained_outcome`` is the pipeline's
        handoff flush when leadership was lost this tick (its pods are
        the new leader's to place), else None."""
        drained = None
        if self.leading and self.chaos.fire("leader.lost"):
            # injected leadership loss: surrender the lease and step
            # down THIS tick (the next tick may re-acquire — under a new
            # epoch, through full recovery — or a contender takes over;
            # either way the flap is a real grant boundary)
            self.elector.release()
            drained = self._on_loss("injected leadership loss")
            return self.leading, drained
        if (
            not self.leading
            and self.governor is not None
            and not self.governor.may_boot()
        ):
            # crash-loop governor: this incarnation died K times within
            # the horizon — its boot backoff has not elapsed, so it must
            # not even CONTEND for the lease (a crash-looping candidate
            # that keeps winning elections starves healthy standbys)
            return False, None
        if (
            not self.leading
            and self.acquire_gate is not None
            and not self.acquire_gate()
        ):
            # multi-standby election: another live candidate is the
            # designated successor for this lease — stand down rather
            # than race it (the gate is advisory; if the designee dies
            # the ranking re-points and this candidate contends)
            return False, None
        ok = self.elector.try_acquire_or_renew()
        if self.leading and not ok:
            # a leader's failed renew means the CAS lost: the record
            # moved under us (taken over or released) — step down NOW;
            # renewing later under the old epoch would be fenced anyway
            drained = self._on_loss("lease renew lost")
        elif ok and not self.leading:
            try:
                self._on_takeover()
            except BootCrashError as exc:
                # the boot crashed: record a governed death (snapshot →
                # decide → ledger; K rapid deaths impose backoff and a
                # DEGRADED next boot), surrender the half-acquired lease
                # and stay standby — the backoff gate above throttles
                # the retry instead of letting the loop spin hot
                if self.governor is not None:
                    self.governor.note_death(reason=repr(exc))
                self.elector.release()
                self.leading = False
                if self.sched is not None:
                    self.sched.extender.health.set(
                        "leader", False, f"boot crashed: {exc!r}"
                    )
                return False, None
        return self.leading, drained

    def step_down(self):
        """Voluntary handoff: release the lease and drain."""
        if not self.leading:
            return None
        self.elector.release()
        return self._on_loss("voluntary step-down")
