"""Leadership state machine tying election, fencing, journal and
recovery into one coordinator (HA failover PR).

One :class:`LeaderCoordinator` per scheduler instance. ``tick()`` runs a
single election protocol step (testable without threads or wall-clock
sleeps — inject the elector's clock) and drives the transitions:

* **takeover** — the lease is acquired under a fresh epoch; the shared
  :class:`~..core.journal.EpochFence` adopts it (deposing every older
  grant at the commit/channel boundaries), then
  :func:`~.recovery.recover_scheduler` rebuilds the world from the
  statehub resync + journal replay and only THEN grants the scheduler
  its epoch — a half-recovered instance can never commit.
* **loss** — the scheduler revokes its own epoch immediately (local
  sentinel −1: every in-flight commit is fenced regardless of who holds
  the new grant), then the pipeline drains for handoff: speculation
  discarded, trailing commit flushed through the fencing check, state
  surfaced on ``/healthz``.

Named chaos point (ROADMAP rule): ``leader.lost`` — evaluated at the
top of a leader's tick; firing force-releases the lease, so the same
seed yields the same flap schedule.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..chaos import NULL_INJECTOR
from .recovery import RecoveryReport, recover_scheduler


class LeaderCoordinator:
    """Election steps + fenced grant/revoke for one scheduler instance."""

    def __init__(
        self,
        sched,
        elector,
        fence,
        journal,
        hub=None,
        pipeline=None,
        verify_recovery: bool = True,
        chaos=None,
    ):
        self.sched = sched
        self.elector = elector
        self.fence = fence
        self.journal = journal
        self.hub = hub
        self.pipeline = pipeline
        self.verify_recovery = verify_recovery
        self.chaos = chaos or getattr(sched, "chaos", None) or NULL_INJECTOR
        self.leading = False
        #: report of the most recent takeover's recovery
        self.last_recovery: Optional[RecoveryReport] = None
        sched.extender.health.set("leader", True, "standby (no grant yet)")

    # ---- transitions ----

    def _on_takeover(self) -> None:
        epoch = self.elector.current_epoch() or self.fence.advance()
        # the shared fence mirrors the lease's epoch: adopting it is what
        # deposes every older grant at the commit/channel boundaries
        self.fence.adopt(epoch)
        self.last_recovery = recover_scheduler(
            self.sched,
            self.journal,
            hub=self.hub,
            epoch=epoch,
            verify=self.verify_recovery,
        )
        self.leading = True

    def _on_loss(self, reason: str):
        self.leading = False
        self.sched.revoke_leadership(f"standby ({reason})")
        drained = None
        if self.pipeline is not None:
            drained = self.pipeline.drain_for_handoff()
        return drained

    # ---- public surface ----

    def tick(self) -> Tuple[bool, Optional[object]]:
        """One election protocol step. Returns ``(is_leader,
        drained_outcome)`` — ``drained_outcome`` is the pipeline's
        handoff flush when leadership was lost this tick (its pods are
        the new leader's to place), else None."""
        drained = None
        if self.leading and self.chaos.fire("leader.lost"):
            # injected leadership loss: surrender the lease and step
            # down THIS tick (the next tick may re-acquire — under a new
            # epoch, through full recovery — or a contender takes over;
            # either way the flap is a real grant boundary)
            self.elector.release()
            drained = self._on_loss("injected leadership loss")
            return self.leading, drained
        ok = self.elector.try_acquire_or_renew()
        if self.leading and not ok:
            # a leader's failed renew means the CAS lost: the record
            # moved under us (taken over or released) — step down NOW;
            # renewing later under the old epoch would be fenced anyway
            drained = self._on_loss("lease renew lost")
        elif ok and not self.leading:
            self._on_takeover()
        return self.leading, drained

    def step_down(self):
        """Voluntary handoff: release the lease and drain."""
        if not self.leading:
            return None
        self.elector.release()
        return self._on_loss("voluntary step-down")
