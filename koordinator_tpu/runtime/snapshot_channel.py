"""gRPC snapshot/delta channel: control plane → TPU solver sidecar.

The north-star architecture (SURVEY.md §2.8) keeps the Go scheduler shim
and ships NodeInfo/PodInfo state to the JAX solver over gRPC — the same
single-proto discipline the reference uses for its only RPC surface
(``apis/runtime/v1alpha1/api.proto``). This module is the Python sidecar:

- ``SolverService``  — applies ``SnapshotDelta`` batches to a live
  ``ClusterSnapshot`` and answers ``Nominate`` with solver assignments.
  Nominations are exactly that (SURVEY §7 "hard parts a"): the control
  plane revalidates at Reserve time and failed pods re-enter the batch.
- ``SolverClient``   — typed stubs for the Go-side role, used by tests
  and the simulator.

The image ships protoc without the grpc python plugin, so the service is
registered through ``grpc.method_handlers_generic_handler`` instead of
generated stubs; the wire contract lives in ``proto/snapshot.proto``.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from ..api.types import Node, NodeMetric, NodeStatus, ObjectMeta, Pod, PodSpec, ResourceMetric
from ..chaos import NULL_INJECTOR, FaultInjector
from ..core.snapshot import ClusterSnapshot
from ..scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from ..utils.retry import RetryPolicy
from .proto import snapshot_pb2 as pb

SERVICE_NAME = "koordinator_tpu.runtime.SolverService"


# ---------------------------------------------------------------------------
# Typed channel errors: callers branch on exception type, never on raw
# grpc.RpcError status plumbing (robustness PR satellite).
# ---------------------------------------------------------------------------


class ChannelError(Exception):
    """Base for all snapshot-channel failures; carries the gRPC status
    code (None for injected/local failures)."""

    def __init__(self, message: str, code: Optional[object] = None):
        super().__init__(message)
        self.code = code


class ChannelUnavailable(ChannelError):
    """Transport-level failure (UNAVAILABLE / dropped RPC) — retryable."""


class ChannelTimeout(ChannelError):
    """Per-call deadline exceeded — retryable."""


class ChannelCallError(ChannelError):
    """Any other gRPC status (INVALID_ARGUMENT, INTERNAL, …) — the call
    reached the server and failed; retrying the same payload is the
    caller's policy decision, not the transport's."""


class ChannelFenced(ChannelError):
    """The server refused the call because the caller's leadership epoch
    is stale (HA fencing at the snapshot-channel boundary) — NOT
    retryable: the deposed leader must stand down, not re-send."""


class ChannelBreakerOpen(ChannelError):
    """The client's circuit breaker is OPEN (overload-control PR): K
    consecutive channel failures tripped it, and the cooldown window
    has not yet admitted a half-open probe. The call failed FAST —
    nothing left the process — so the caller should degrade (host-
    reference ladder, deferred sync) instead of paying retry backoff
    against a dead channel. NOT retryable by the RetryPolicy: the
    breaker IS the retry governor while it is open."""


_RETRYABLE_ERRORS = (ChannelUnavailable, ChannelTimeout)

#: failures that count toward opening the breaker: the channel itself
#: misbehaved. Fencing is deliberately excluded (a deposed leader's
#: refusal is a correctness verdict, not channel death), as is the
#: breaker's own fast-fail.
_BREAKER_COUNTED = (ChannelUnavailable, ChannelTimeout, ChannelCallError)

#: metadata key carrying the caller's fencing epoch (the proto stays
#: unchanged — fencing is transport-level, like an authz header)
EPOCH_METADATA_KEY = "x-leader-epoch"
#: metadata key scoping the epoch to ONE shard (PR 6, horizontally
#: partitioned control plane): each shard's epoch history is
#: independent, so the server keeps a per-shard high watermark — shard
#: 3's takeover must not fence shard 1's still-live owner
SHARD_METADATA_KEY = "x-shard-id"


def _map_rpc_error(call: str, exc: grpc.RpcError) -> ChannelError:
    code = exc.code() if callable(getattr(exc, "code", None)) else None
    detail = exc.details() if callable(getattr(exc, "details", None)) else ""
    msg = f"{call}: {code} {detail or ''}".strip()
    if code == grpc.StatusCode.DEADLINE_EXCEEDED:
        return ChannelTimeout(msg, code)
    if code == grpc.StatusCode.UNAVAILABLE:
        return ChannelUnavailable(msg, code)
    if code == grpc.StatusCode.FAILED_PRECONDITION:
        return ChannelFenced(msg, code)
    return ChannelCallError(msg, code)


def _vec_to_list(config, rl) -> list:
    return [float(x) for x in config.res_vector(rl)]


def _rl_from_vec(config, vec: pb.ResourceVector) -> dict:
    return {
        res: float(v)
        for res, v in zip(config.resources, vec.values)
        if v
    }


class SolverService:
    """Server side: one live snapshot + solver, mutated by deltas."""

    def __init__(
        self,
        snapshot: Optional[ClusterSnapshot] = None,
        args: Optional[LoadAwareArgs] = None,
        batch_bucket: int = 4096,
        assume_ttl: float = 900.0,
        mesh=None,
    ):
        self.snapshot = snapshot or ClusterSnapshot()
        self.args = args or LoadAwareArgs()
        self.scheduler = BatchScheduler(
            self.snapshot, self.args, batch_bucket=batch_bucket, mesh=mesh
        )
        self.revision = 0
        #: seconds an optimistic nominate-side assume survives without a
        #: confirming pod_assumed sync (kube-scheduler's assumed-pod
        #: expiration; bounds the capacity leak of a nomination the
        #: control plane rejected and never reserved)
        self.assume_ttl = assume_ttl
        self._lock = threading.Lock()
        #: highest leadership epoch observed over the channel (HA PR):
        #: calls stamped with an OLDER epoch are refused
        #: (FAILED_PRECONDITION → ChannelFenced client-side), so a
        #: deposed leader's in-flight delta/nominate can never mutate or
        #: read the solver's world after its successor has spoken.
        #: Callers without the metadata (non-HA deployments) pass freely.
        self.leader_epoch = 0
        #: per-shard epoch high watermarks (PR 6): calls carrying
        #: x-shard-id are fenced against THEIR shard's history only
        self.shard_epochs: dict = {}

    def _check_epoch(self, call: str, ctx) -> None:
        """Adopt/enforce the caller's fencing epoch from gRPC metadata.
        Must be called under ``self._lock`` so adopt-vs-refuse is atomic
        with the guarded mutation. A call scoped with
        ``x-shard-id`` fences against that shard's own watermark — the
        per-shard analog of the global check."""
        if ctx is None:
            return
        raw = None
        raw_shard = None
        try:
            for k, v in ctx.invocation_metadata() or ():
                if k == EPOCH_METADATA_KEY:
                    raw = v
                elif k == SHARD_METADATA_KEY:
                    raw_shard = v
        except TypeError:
            return
        if raw is None:
            return
        try:
            epoch = int(raw)
            shard = int(raw_shard) if raw_shard is not None else None
        except (TypeError, ValueError):
            # a PRESENT but unparseable epoch/shard must not pass
            # unfenced — the caller claims to be epoch-guarded, so an
            # unprovable claim is rejected, not waved through
            ctx.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"{call}: malformed fencing metadata "
                f"epoch={raw!r} shard={raw_shard!r}",
            )
        if shard is not None:
            high = self.shard_epochs.get(shard, 0)
            if epoch < high:
                ctx.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"{call}: stale leader epoch {epoch} for shard "
                    f"{shard} (current {high})",
                )
            self.shard_epochs[shard] = epoch
            return
        if epoch < self.leader_epoch:
            ctx.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"{call}: stale leader epoch {epoch} "
                f"(current {self.leader_epoch})",
            )
        self.leader_epoch = epoch

    # ---- rpc bodies ----

    def sync(self, delta: pb.SnapshotDelta, _ctx=None) -> pb.SyncAck:
        cfg = self.snapshot.config
        now = delta.now or time.time()
        with self._lock:
            self._check_epoch("sync", _ctx)
            # Generation-gap detection (informer re-list analog): a delta
            # that is not exactly the next revision was dropped/reordered
            # in transit — applying it would silently diverge the solver's
            # world view, so REJECT and demand a full resync instead. A
            # fresh solver (revision 0) is mid-stream blind: it accepts
            # only a stream head (revision ≤ 1) or a full re-list,
            # otherwise a restarted solver would adopt one incremental
            # delta as its entire world.
            if (
                delta.revision
                and not delta.full
                and delta.revision != self.revision + 1
                and not (self.revision == 0 and delta.revision <= 1)
            ):
                return pb.SyncAck(
                    applied_revision=self.revision,
                    node_count=self.snapshot.node_count,
                    resync_required=True,
                    expected_revision=self.revision + 1,
                )
            if delta.full:
                # complete world state follows: start from nothing. Quota
                # charges and device/NUMA holds of pods that vanished with
                # the old world must not leak — managers reset too, and
                # exact holds are re-established as pods re-commit (the
                # reference rebuilds its device cache from pod annotations
                # on re-list; the channel's pod_assumed entries re-charge
                # node capacity here).
                self.snapshot.reset()
                sched = self.scheduler
                sched._bound_nodes.clear()
                if sched.quotas is not None:
                    sched.quotas.reset_usage()
                if sched.devices is not None:
                    sched.devices.reset_allocations()
                if sched.numa is not None:
                    sched.numa.reset_allocations()
            for up in delta.node_upserts:
                self.snapshot.upsert_node(
                    Node(
                        meta=ObjectMeta(name=up.name),
                        status=NodeStatus(
                            allocatable=_rl_from_vec(cfg, up.allocatable)
                        ),
                        unschedulable=up.unschedulable,
                    )
                )
            for name in delta.node_removes:
                self.snapshot.remove_node(name)
            for mu in delta.metric_updates:
                self.snapshot.set_node_metric(
                    NodeMetric(
                        meta=ObjectMeta(name=mu.name),
                        node_usage=ResourceMetric(usage=_rl_from_vec(cfg, mu.usage)),
                        prod_usage=ResourceMetric(
                            usage=_rl_from_vec(cfg, mu.prod_usage)
                        ),
                        update_time=mu.update_time or now,
                    ),
                    now=now,
                )
            skipped = 0
            for pa in delta.pod_assumed:
                applied = self.snapshot.assume_pod(
                    Pod(
                        meta=ObjectMeta(name=pa.uid, uid=pa.uid),
                        spec=PodSpec(
                            requests=_rl_from_vec(cfg, pa.requests),
                            priority=pa.priority or None,
                        ),
                    ),
                    pa.node,
                    estimated=np.asarray(pa.estimated.values, np.float32)
                    if pa.estimated.values
                    else None,
                    now=now,
                )
                if not applied:
                    skipped += 1
            for uid in delta.pod_forgotten:
                self.snapshot.forget_pod(uid)
            if delta.revision:
                self.revision = delta.revision
            else:
                self.revision += 1
            return pb.SyncAck(
                applied_revision=self.revision,
                node_count=self.snapshot.node_count,
                assumes_skipped=skipped,
            )

    def nominate(self, req: pb.NominateRequest, _ctx=None) -> pb.NominateResponse:
        cfg = self.snapshot.config
        pods = []
        for pp in req.pods:
            pods.append(
                Pod(
                    meta=ObjectMeta(name=pp.uid, uid=pp.uid),
                    spec=PodSpec(
                        requests=_rl_from_vec(cfg, pp.requests),
                        estimated=_rl_from_vec(cfg, pp.estimated)
                        if pp.estimated.values
                        else None,
                        priority=pp.priority
                        or (9000 if pp.is_prod else 5000),
                    ),
                )
            )
        t0 = time.perf_counter()
        with self._lock:
            self._check_epoch("nominate", _ctx)
            self.snapshot.expire_assumed(time.time(), self.assume_ttl)
            out = self.scheduler.schedule(pods)
            rev = self.revision
        resp = pb.NominateResponse(
            at_revision=rev, solve_ms=(time.perf_counter() - t0) * 1e3
        )
        for pod, node in out.bound:
            resp.nominations.add(pod_uid=pod.meta.uid, node=node)
        for pod in out.unschedulable:
            resp.nominations.add(pod_uid=pod.meta.uid, node="")
        return resp

    def get_config(self, _req: pb.SolverConfigRequest, _ctx=None) -> pb.SolverConfig:
        cfg = self.snapshot.config
        return pb.SolverConfig(
            resources=list(cfg.resources),
            usage_thresholds=pb.ResourceVector(
                values=_vec_to_list(cfg, self.args.usage_thresholds)
            ),
            prod_thresholds=pb.ResourceVector(
                values=_vec_to_list(cfg, self.args.prod_usage_thresholds)
            ),
        )

    # ---- grpc wiring (no generated stubs: generic handler) ----

    def generic_handler(self) -> grpc.GenericRpcHandler:
        handlers = {
            "Sync": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self.sync(req, ctx),
                request_deserializer=pb.SnapshotDelta.FromString,
                response_serializer=pb.SyncAck.SerializeToString,
            ),
            "Nominate": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self.nominate(req, ctx),
                request_deserializer=pb.NominateRequest.FromString,
                response_serializer=pb.NominateResponse.SerializeToString,
            ),
            "GetConfig": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self.get_config(req, ctx),
                request_deserializer=pb.SolverConfigRequest.FromString,
                response_serializer=pb.SolverConfig.SerializeToString,
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


def serve(
    service: SolverService,
    address: str = "127.0.0.1:0",
    max_workers: int = 4,
) -> tuple[grpc.Server, int]:
    """Start the sidecar server; returns (server, bound_port)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((service.generic_handler(),))
    port = server.add_insecure_port(address)
    server.start()
    return server, port


class SolverClient:
    """The control-plane side of the channel (what the Go shim speaks).

    Hardened surface (robustness PR):

    * every call can carry a per-call deadline (``timeout_s``; default
      None = unbounded, because a cold solver's first Nominate pays the
      JIT compile — set a deadline once the channel is warm) and maps
      ``grpc.RpcError`` to the typed :class:`ChannelError` hierarchy —
      callers never see raw status plumbing;
    * an optional :class:`~..utils.retry.RetryPolicy` drives backoff over
      the *retryable* subset (UNAVAILABLE / DEADLINE_EXCEEDED), counting
      every retry into ``retry_attempts_total{site="channel.<call>"}``;
    * named chaos points ``channel.{sync,nominate,get_config}.drop`` /
      ``.delay`` inject dropped and delayed RPCs deterministically (a
      drop raises :class:`ChannelUnavailable` *before* the wire, so the
      delta genuinely never reached the server — the generation-gap
      resync protocol is what repairs the stream afterwards).
    """

    def __init__(
        self,
        target: str,
        timeout_s: Optional[float] = None,
        retry: Optional[RetryPolicy] = None,
        chaos: Optional[FaultInjector] = None,
        retry_counter=None,
        fence=None,
        breaker=None,
        timeout_warm_s: Optional[float] = None,
    ):
        self.timeout_s = timeout_s
        #: warm-after-first-success deadline (gray-failure containment
        #: PR): the COLD first call stays unbounded (it pays the JIT
        #: compile — a deadline there would always fire), but once any
        #: call has succeeded the channel is warm and a steady-state
        #: call that hangs is a gray failure, not a compile. Ignored
        #: while ``timeout_s`` is set (an explicit deadline wins).
        self.timeout_warm_s = timeout_warm_s
        self._warm = False
        self.retry = retry
        self.chaos = chaos or NULL_INJECTOR
        self.retry_counter = retry_counter
        #: circuit breaker (overload-control PR): a
        #: :class:`~.overload.CircuitBreaker`. K consecutive channel
        #: failures open it; while open, calls raise
        #: :class:`ChannelBreakerOpen` BEFORE the wire (and before the
        #: RetryPolicy can spin) until the cooldown admits a half-open
        #: probe. None = unmetered, the pre-PR behavior.
        self.breaker = breaker
        #: HA fencing: optional EpochFence + the epoch this client's
        #: leadership grant carries (set_epoch on takeover). When wired,
        #: every call is (a) checked locally — a deposed leader's delta
        #: never leaves the process — and (b) stamped into gRPC metadata
        #: so the SERVER refuses stale writers even when the local fence
        #: was bypassed (two independent layers, like journal fencing).
        self.fence = fence
        self.epoch: Optional[int] = None
        #: shard scoping for the stamped epoch (PR 6): when set, the
        #: server fences this client against ITS shard's watermark only
        self.shard: Optional[int] = None
        self._channel = grpc.insecure_channel(target)
        self._sync = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Sync",
            request_serializer=pb.SnapshotDelta.SerializeToString,
            response_deserializer=pb.SyncAck.FromString,
        )
        self._nominate = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Nominate",
            request_serializer=pb.NominateRequest.SerializeToString,
            response_deserializer=pb.NominateResponse.FromString,
        )
        self._get_config = self._channel.unary_unary(
            f"/{SERVICE_NAME}/GetConfig",
            request_serializer=pb.SolverConfigRequest.SerializeToString,
            response_deserializer=pb.SolverConfig.FromString,
        )

    _SHARD_UNSET = object()

    def set_epoch(self, epoch: Optional[int], shard=_SHARD_UNSET) -> None:
        """Adopt the leadership epoch this client's calls carry (None =
        un-fenced, the non-HA default). ``shard`` scopes the epoch to
        one shard's fencing history (PR 6): the server then compares it
        against that shard's high watermark instead of the global one.
        Omitting ``shard`` PRESERVES the current scoping — a re-granted
        shard owner calling the PR 5-style ``set_epoch(epoch)`` must not
        silently fall back to the global watermark; pass ``shard=None``
        explicitly to clear the scope."""
        self.epoch = epoch
        if shard is not SolverClient._SHARD_UNSET:
            self.shard = shard

    def _call(self, name: str, stub, req):
        chaos = self.chaos
        breaker = self.breaker

        def once():
            if breaker is not None and not breaker.allow():
                # fail FAST: the channel is known-dead and the cooldown
                # has not yet admitted a probe — no fence read, no wire,
                # no retry backoff
                raise ChannelBreakerOpen(
                    f"{name}: circuit breaker open "
                    f"({breaker.state_name})", None
                )
            if self.fence is not None and self.epoch is not None:
                # local fencing: raises StaleEpochError when our grant
                # was superseded — the delta never reaches the wire
                self.fence.check(self.epoch)
            if chaos.fire(f"channel.{name}.drop"):
                raise ChannelUnavailable(
                    f"{name}: injected RPC drop", None
                )
            if chaos.fire("channel.breaker_storm"):
                # named storm point (overload-control PR): a persistent
                # channel brownout — every call fails at the transport
                # until the schedule exhausts, which is exactly the
                # shape that must trip the breaker instead of burning
                # per-call retry ladders
                raise ChannelUnavailable(
                    f"{name}: injected channel storm", None
                )
            chaos.fire(f"channel.{name}.delay")
            md = None
            if self.epoch is not None:
                md = ((EPOCH_METADATA_KEY, str(self.epoch)),)
                if self.shard is not None:
                    md += ((SHARD_METADATA_KEY, str(self.shard)),)
            timeout = self.timeout_s
            if timeout is None and self._warm:
                timeout = self.timeout_warm_s
            try:
                out = stub(req, timeout=timeout, metadata=md)
            except grpc.RpcError as exc:
                raise _map_rpc_error(name, exc) from exc
            self._warm = True
            return out

        def metered():
            # one breaker verdict per ATTEMPT (the retry policy's
            # attempts each count — K consecutive failures open it
            # regardless of how they were grouped into calls)
            try:
                out = once()
            except ChannelBreakerOpen:
                # the breaker's own fast-fail: this call was never
                # admitted, so it must not touch the probe slot a
                # concurrent admitted call may hold
                raise
            except _BREAKER_COUNTED:
                if breaker is not None:
                    breaker.record_failure()
                raise
            except BaseException:
                # an outcome that says nothing about channel health
                # (fencing — local StaleEpochError or ChannelFenced —
                # or any unexpected error): release the probe slot
                # uncounted, or a HALF_OPEN breaker would wedge with
                # its probe permanently in flight
                if breaker is not None:
                    breaker.abort_probe()
                raise
            if breaker is not None:
                breaker.record_success()
            return out

        if self.retry is None:
            return metered()
        return self.retry.run(
            metered,
            retry_on=_RETRYABLE_ERRORS,
            site=f"channel.{name}",
            counter=self.retry_counter,
        )

    def sync(self, delta: pb.SnapshotDelta) -> pb.SyncAck:
        return self._call("sync", self._sync, delta)

    def sync_with_resync(self, delta: pb.SnapshotDelta, full_state_fn) -> pb.SyncAck:
        """Send a delta; when the solver reports a generation gap, answer
        with the full world state from ``full_state_fn() ->
        SnapshotDelta`` (marked full=true, carrying this delta's
        revision) — the informer re-list on disconnect."""
        ack = self.sync(delta)
        if not ack.resync_required:
            return ack
        full = full_state_fn()
        full.full = True
        if not full.revision:
            full.revision = delta.revision
        return self.sync(full)

    def nominate(self, req: pb.NominateRequest) -> pb.NominateResponse:
        return self._call("nominate", self._nominate, req)

    def get_config(self) -> pb.SolverConfig:
        return self._call("get_config", self._get_config, pb.SolverConfigRequest())

    def close(self) -> None:
        self._channel.close()
