"""ClusterStateHub: the in-process apiserver analog wiring informers into
the production components.

Round-2 review finding: ``utils.informer`` existed with tests but drove
nothing — scheduler/manager state still arrived via direct setters. This
module closes that: one :class:`~..utils.informer.ObjectTracker` per
resource kind (Node, NodeMetric, Pod, Device, ElasticQuota, Reservation,
PodGroup) fans out LIST+WATCH streams, and :meth:`wire_scheduler` registers
handlers that apply events to the live components — ``ClusterSnapshot``,
``GroupQuotaManager``, ``DeviceManager``, ``ReservationManager``,
``PodGroupManager`` — exactly how the reference's generated informers feed
the scheduler cache (``pkg/scheduler/eventhandlers``,
``frameworkext/informer/``). A killed watch (disconnect / overflow)
triggers the informer's automatic re-list, so consumer state re-converges
without any component-specific repair code; ``disconnect()`` is the chaos
lever the longrun test uses to prove it.
"""

from __future__ import annotations

import time as _time
from typing import List, Optional

from ..api.types import ReservationPhase
from ..utils.informer import Informer, ObjectTracker


def _key(obj) -> str:
    ns = getattr(obj.meta, "namespace", "") or ""
    return f"{ns}/{obj.meta.name}" if ns else obj.meta.name



def _locked(lock, fn):
    """Run an informer handler under the snapshot's coarse lock: handler
    threads must never interleave with a scheduling cycle's reads/writes
    (the reference serializes cache mutations the same way)."""

    def handler(key, obj):
        with lock:
            fn(key, obj)

    return handler


class ClusterStateHub:
    """Versioned trackers per resource kind + informer wiring."""

    def __init__(
        self,
        resync_interval_s: float = 0.0,
        chaos=None,
        health=None,
        error_registry=None,
    ):
        from ..chaos import NULL_INJECTOR

        #: fault injector + health registry threaded into every informer
        #: this hub creates (chaos points ``informer.*``; /healthz rows
        #: ``informer.<kind>``)
        self.chaos = chaos or NULL_INJECTOR
        self.health = health
        #: metrics registry for informer exceptions_total /
        #: retry_attempts_total (e.g. the scheduler registry)
        self.error_registry = error_registry
        self._informer_seq = 0
        # the trackers share the hub's injector so informer.silent_stall
        # (gray-failure containment PR) can mute delivery at the source
        # while every watch stays connected
        self.nodes = ObjectTracker(chaos=self.chaos)
        self.node_metrics = ObjectTracker(chaos=self.chaos)
        self.pods = ObjectTracker(chaos=self.chaos)
        self.devices = ObjectTracker(chaos=self.chaos)
        self.quotas = ObjectTracker(chaos=self.chaos)
        self.reservations = ObjectTracker(chaos=self.chaos)
        self.pod_groups = ObjectTracker(chaos=self.chaos)
        #: NodeResourceTopology reports (the koordlet's CR writes)
        self.topologies = ObjectTracker(chaos=self.chaos)
        self.resync_interval_s = resync_interval_s
        self.informers: List[Informer] = []
        #: snapshot-id → the node Informer that applies nodes into that
        #: snapshot; lets wire_scheduler chain its pending-bind drain onto
        #: the SAME informer (same thread, handler order = registration
        #: order) instead of racing it from an independent stream
        self._snapshot_node_informers: dict = {}
        self._trackers = (
            self.nodes,
            self.node_metrics,
            self.pods,
            self.devices,
            self.quotas,
            self.reservations,
            self.pod_groups,
            self.topologies,
        )

    # ---- publish side (what the control plane / sim writes) ----

    def publish(self, tracker: ObjectTracker, obj) -> int:
        return tracker.upsert(_key(obj), obj)

    def delete(self, tracker: ObjectTracker, obj) -> Optional[int]:
        return tracker.delete(_key(obj))

    def _informer(self, tracker: ObjectTracker, kind: str) -> Informer:
        self._informer_seq += 1
        return Informer(
            tracker,
            self.resync_interval_s,
            chaos=self.chaos,
            health=self.health,
            name=f"informer.{kind}.{self._informer_seq}",
            error_registry=self.error_registry,
        )

    def disconnect(self) -> None:
        """Chaos lever: sever every open watch (apiserver restart). Each
        informer re-lists on its next poll and re-converges."""
        for t in self._trackers:
            t.close_all_watches()

    # ---- consume side ----

    def wire_snapshot(self, snap, node_filter=None) -> List[Informer]:
        """Node + NodeMetric informers feeding a ClusterSnapshot — the
        minimal consumer set (manager/descheduler binaries).

        ``node_filter`` (PR 6, horizontal partitioning): a predicate on
        node NAME scoping this snapshot to one shard of the cluster —
        nodes (and their metrics) outside the shard never enter it, so
        a shard owner's resident state is exactly its partition."""
        lock = snap.lock

        def _owned(name: str) -> bool:
            return node_filter is None or node_filter(name)

        def _node_upsert(_k, o):
            if _owned(o.meta.name):
                snap.upsert_node(o)

        def _node_delete(_k, o):
            if _owned(o.meta.name):
                snap.remove_node(o.meta.name)

        node_inf = self._informer(self.nodes, 'nodes')
        node_inf.add_handlers(
            on_add=_locked(lock, _node_upsert),
            on_update=_locked(lock, _node_upsert),
            on_delete=_locked(lock, _node_delete),
        )

        metric_inf = self._informer(self.node_metrics, 'node_metrics')

        def _metric(_k, m):
            if not _owned(m.meta.name):
                return
            snap.set_node_metric(
                m,
                now=(m.update_time + 1 if m.update_time else _time.time()),
            )

        metric_inf.add_handlers(
            on_add=_locked(lock, _metric),
            on_update=_locked(lock, _metric),
        )
        self._snapshot_node_informers[id(snap)] = node_inf
        informers = [node_inf, metric_inf]
        self.informers.extend(informers)
        return informers

    def wire_scheduler(
        self,
        sched,
        reservations=None,
        include_snapshot: bool = True,
        node_filter=None,
    ) -> List[Informer]:
        """Informers driving a BatchScheduler's full component set. The
        returned informers are registered but not started — call
        :meth:`start`. ``include_snapshot=False`` when
        :meth:`wire_snapshot` already wired this scheduler's snapshot.

        ``node_filter`` (PR 6): scopes the wiring to one shard — nodes,
        node metrics, per-node devices/topologies and pods BOUND on
        foreign nodes are skipped entirely (a foreign bind parked in
        ``pending_binds`` would otherwise leak forever: its node never
        arrives in this snapshot). Unbound pods are shard-agnostic at
        this layer; routing decides who schedules them."""
        snap = sched.snapshot

        def _owned(name) -> bool:
            return node_filter is None or (
                name is not None and node_filter(name)
            )

        #: wire_snapshot self-registers; ``extras`` are registered at the
        #: end of this method — the returned list carries both
        informers: List[Informer] = []
        extras: List[Informer] = []
        if include_snapshot:
            informers.extend(self.wire_snapshot(snap, node_filter))

        pod_inf = self._informer(self.pods, 'pods')
        #: binds observed before their node (the pod and node informers
        #: are independent streams — cross-kind ordering is not
        #: guaranteed); drained when the node arrives
        pending_binds: dict = {}

        def _pod_upsert(_k, pod):
            if pod.spec.node_name and not _owned(pod.spec.node_name):
                return  # bound on a foreign shard — its owner tracks it
            # a pod observed bound (spec.nodeName set): if this scheduler
            # already assumed it, the bind CONFIRMS the existing charge
            # (estimates/amplification intact — the reference cache's
            # assume→AddPod flow); otherwise (external bind / restart
            # recovery) it is charged fresh as a confirmed assume
            if pod.spec.node_name:
                if snap.is_assumed(pod.meta.uid):
                    snap.confirm_pod(pod.meta.uid)
                elif not snap.assume_pod(
                    pod, pod.spec.node_name, confirmed=True
                ):
                    # node not (yet) known: park the bind until the node
                    # informer delivers it
                    pending_binds[pod.meta.uid] = pod
                    # the node may have landed between the failed assume
                    # and the park (the drain would then have run on an
                    # empty map) — re-check closes the interleaving
                    if snap.node_id(pod.spec.node_name) is None:
                        return
                    pending_binds.pop(pod.meta.uid, None)
                    if not snap.assume_pod(
                        pod, pod.spec.node_name, confirmed=True
                    ):
                        return
                sched._bound_nodes[pod.meta.uid] = pod.spec.node_name
                if reservations is not None:
                    reservations.ingest_operating_pod(pod)

        def _pod_delete(_k, pod):
            if pod.spec.node_name and not _owned(pod.spec.node_name):
                return  # foreign shard's bind — its owner releases it
            # full release across every component that may hold state for
            # the pod (scheduler cache RemovePod + plugin unreserve)
            pending_binds.pop(pod.meta.uid, None)
            sched.evict_for_preemption(pod)
            if reservations is not None:
                reservations.remove_operating_pod(pod.meta.name)

        lock = snap.lock
        pod_inf.add_handlers(
            on_add=_locked(lock, _pod_upsert),
            on_update=_locked(lock, _pod_upsert),
            on_delete=_locked(lock, _pod_delete),
        )
        extras.append(pod_inf)

        def _drain_binds(_k, node):
            for uid, pod in list(pending_binds.items()):
                if pod.spec.node_name == node.meta.name:
                    pending_binds.pop(uid, None)
                    _pod_upsert(uid, pod)

        snap_node_inf = self._snapshot_node_informers.get(id(snap))
        if snap_node_inf is not None:
            # chain the drain onto the informer that applies nodes into
            # this snapshot: handlers run in registration order on ONE
            # thread, so the drain always observes the node already
            # upserted — no independent stream to race (a drain racing
            # ahead of upsert_node could park a bind forever and leave the
            # node permanently under-charged)
            snap_node_inf.add_handlers(
                on_add=_locked(lock, _drain_binds),
                on_update=_locked(lock, _drain_binds),
            )
        else:
            # snapshot wired elsewhere (e.g. a different hub): fall back
            # to a dedicated informer — ordering vs that foreign wiring is
            # not guaranteed, so hubs used this way should set a nonzero
            # resync_interval_s as the repair backstop
            drain_inf = self._informer(self.nodes, 'nodes_drain')
            drain_inf.add_handlers(
                on_add=_locked(lock, _drain_binds),
                on_update=_locked(lock, _drain_binds),
            )
            extras.append(drain_inf)

        if sched.devices is not None:
            dev_inf = self._informer(self.devices, 'devices')

            def _dev(fn):
                # Device CRs are named by node — shard-scoped like nodes
                def h(_k, d):
                    if _owned(d.meta.name):
                        fn(d)

                return h

            dev_inf.add_handlers(
                on_add=_locked(lock, _dev(sched.devices.upsert_device)),
                on_update=_locked(lock, _dev(sched.devices.upsert_device)),
                on_delete=_locked(
                    lock,
                    _dev(lambda d: sched.devices.remove_device(d.meta.name)),
                ),
            )
            extras.append(dev_inf)

        if sched.numa is not None:
            topo_inf = self._informer(self.topologies, 'topologies')

            def _topo(fn):
                def h(_k, t):
                    if _owned(t.meta.name):
                        fn(t)

                return h

            topo_inf.add_handlers(
                on_add=_locked(lock, _topo(sched.numa.register_from_topology)),
                on_update=_locked(
                    lock, _topo(sched.numa.register_from_topology)
                ),
                on_delete=_locked(
                    lock,
                    _topo(lambda t: sched.numa.unregister_node(t.meta.name)),
                ),
            )
            extras.append(topo_inf)

        if sched.quotas is not None:
            quota_inf = self._informer(self.quotas, 'quotas')
            quota_inf.add_handlers(
                on_add=_locked(lock, lambda k, q: sched.quotas.upsert_quota(q)),
                on_update=_locked(lock, lambda k, q: sched.quotas.upsert_quota(q)),
                on_delete=_locked(
                    lock, lambda k, q: sched.quotas.remove_quota(q.meta.name)
                ),
            )
            extras.append(quota_inf)

        if reservations is not None:
            resv_inf = self._informer(self.reservations, 'reservations')

            from ..api import extension as _ext

            #: only these annotations are spec-bearing for a reservation;
            #: comparing the whole dict would let a purely informational
            #: annotation expire a live AVAILABLE hold and wipe its owner
            #: ledger
            _RESV_SPEC_ANNOTATIONS = (
                _ext.ANNOTATION_RESERVATION_RESTRICTED_OPTIONS,
                _ext.ANNOTATION_EXACT_MATCH_RESERVATION_SPEC,
                _ext.ANNOTATION_RESERVATION_OWNERS,
            )

            def _resv_spec(r):
                ann = r.meta.annotations or {}
                return (
                    dict(r.requests),
                    sorted(
                        (tuple(sorted(o.label_selector.items())), o.namespace or "")
                        for o in r.owners
                    ),
                    r.allocate_once,
                    r.ttl_s,
                    r.allocate_policy,
                    tuple(ann.get(k) for k in _RESV_SPEC_ANNOTATIONS),
                )

            def _resv_upsert(_k, r):
                existing = reservations.get(r.meta.name)
                if existing is None:
                    reservations.add(r)
                elif existing is not r and _resv_spec(existing) != _resv_spec(r):
                    # spec change (requests/owners/TTL/policy/annotations):
                    # release the old incarnation's hold and re-admit the
                    # new spec from PENDING — the reference cache replaces
                    # reservationInfo on update. Status-only republications
                    # fall through untouched (expiring an AVAILABLE hold
                    # for a no-op update would free capacity still in use).
                    reservations.expire_reservation(r.meta.name)
                    r.phase = ReservationPhase.PENDING
                    r.node_name = None
                    reservations.add(r)

            resv_inf.add_handlers(
                on_add=_locked(lock, _resv_upsert),
                on_update=_locked(lock, _resv_upsert),
                on_delete=_locked(
                    lock,
                    lambda k, r: reservations.expire_reservation(r.meta.name),
                ),
            )
            extras.append(resv_inf)

        pg_inf = self._informer(self.pod_groups, 'pod_groups')
        pg_inf.add_handlers(
            on_add=_locked(lock, lambda k, pg: sched.pod_groups.upsert_pod_group(pg)),
            on_update=_locked(
                lock, lambda k, pg: sched.pod_groups.upsert_pod_group(pg)
            ),
        )
        extras.append(pg_inf)

        self.informers.extend(extras)
        return informers + extras

    # ---- lifecycle ----

    def start(self) -> "ClusterStateHub":
        """Start sync threads for informers not yet running (safe to call
        again after wiring more consumers)."""
        for inf in self.informers:
            if inf._thread is None:
                inf.start()
        return self

    def stop(self) -> None:
        for inf in self.informers:
            inf.stop()

    def detach_consumers(self) -> None:
        """Simulated consumer-process death (HA failover PR): stop and
        DROP every informer this hub wired — their watches die with the
        process — while the trackers (the apiserver's world) survive, so
        a recovering scheduler re-wires fresh informers and re-lists.
        ``wait_synced`` afterwards sees only the new consumer's
        informers; a stopped informer would otherwise wedge it."""
        for inf in self.informers:
            inf.stop()
        self.informers = []
        self._snapshot_node_informers.clear()

    def detach(self, informers: List[Informer]) -> None:
        """Detach ONE consumer's informer set (PR 6: a shard handoff or
        a single incarnation's death must not sever every other live
        incarnation's watches the way :meth:`detach_consumers` does).
        The listed informers are stopped and dropped from the hub's
        registry — including the snapshot-node index — while everything
        else keeps running."""
        doomed = set(map(id, informers))
        for inf in informers:
            inf.stop()
        self.informers = [
            inf for inf in self.informers if id(inf) not in doomed
        ]
        self._snapshot_node_informers = {
            k: inf
            for k, inf in self._snapshot_node_informers.items()
            if id(inf) not in doomed
        }

    def wait_synced(self, timeout: float = 10.0) -> bool:
        """Block until every informer observed its tracker's current rv
        (WaitForCacheSync analog)."""
        ok = True
        pairs = zip(self.informers, self._informer_trackers())
        for inf, tracker in pairs:
            _objs, rv = tracker.list()
            ok = inf.wait_synced(rv, timeout) and ok
        return ok

    def _informer_trackers(self):
        return [inf.tracker for inf in self.informers]

    def relists(self) -> int:
        """Total re-list count across informers (1 per informer = just
        the initial sync; more = disconnect/overflow recovery ran)."""
        return sum(inf.relists for inf in self.informers)
