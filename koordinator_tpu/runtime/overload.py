"""QoS-differentiated overload control (robustness tentpole).

Koordinator's premise is that PROD survives pressure because BATCH/FREE
absorb it — yet until this module the rebuild treated every pod
identically under overload: the stream queue was unbounded, storms were
ridden out purely by elastic splits, and a dead solver channel burned
per-call retry budgets forever. The cluster literature is unanimous that
graceful, priority-aware degradation beats uniform queueing under storm
load (DAGOR-style priority admission in "Overload Control for Scaling
WeChat Microservices", SoCC'18; Meta's utilization-aware load shedding).
Three coordinated mechanisms, one module:

* :class:`AdmissionController` — **bounded, QoS-aware admission** at
  ``StreamScheduler.submit``: PROD/MID are always admitted; BATCH/FREE
  are admitted up to a per-band live-queue budget, DEFERRED (parked, not
  fed to cycles) past it, and SHED once deferral outlives the band's age
  limit — with a terminal ``shed`` lifecycle event, a counted metric,
  and a :class:`ShedTicket` so drivers can resubmit after the storm.

* :class:`BrownoutController` — a **monotonic degradation ladder** driven
  by the same SLO burn signals the elastic :class:`TopologyController`
  reads:  L0 normal → L1 pipeline depth capped at 1 → L2 serial solve +
  batch-bucket degrade → L3 defer all BATCH/FREE → L4 shed FREE.
  Escalation needs ``sustain`` consecutive hot ticks, de-escalation
  ``cooldown`` consecutive cold ticks (one step either way — no
  flapping); transitions are journaled to the flight recorder(s),
  surfaced as a ``/healthz`` row and the ``/debug/brownout`` endpoint.
  When the topology controller still has scale-out budget, the ladder
  YIELDS to a split for a bounded number of ticks before degrading —
  prefer adding capacity when possible, brown out during transition
  cooldowns.

* :class:`CircuitBreaker` — a **solver-channel breaker** consulted by
  :class:`~.snapshot_channel.SolverClient`: ``K`` consecutive channel
  failures open it (calls fail fast with ``ChannelBreakerOpen`` instead
  of paying per-call retry backoff), a half-open probe after
  ``cooldown_s`` tests recovery, one success closes it. State rides the
  ``solver_breaker_state`` gauge.

Disabled-mode discipline (the ``test_obs_overhead`` contract): every hot
path this module touches guards on one attribute-is-None check —
``overload=None`` / ``brownout=None`` / ``breaker=None`` cost nothing.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api.extension import PriorityClass
from ..obs.rejections import RejectReason

__all__ = [
    "OverloadConfig",
    "ShedTicket",
    "AdmissionController",
    "BrownoutController",
    "CircuitBreaker",
]


# ---------------------------------------------------------------------------
# Bounded, QoS-aware admission
# ---------------------------------------------------------------------------


#: the bands the admission controller may defer/shed; PROD, MID and
#: unclassified pods are ALWAYS admitted (the whole point of QoS-
#: differentiated co-location is that they never pay for a storm)
SHEDDABLE_BANDS = (PriorityClass.BATCH, PriorityClass.FREE)


@dataclass
class OverloadConfig:
    """Per-band admission budgets. ``band_budget`` bounds the LIVE queue
    depth a band may occupy on one shard's stream (arrivals past it are
    deferred); ``band_age_limit_s`` bounds how long a deferred pod may
    wait for pressure to clear before it is shed (clock units are the
    caller's — sim cycles in the soaks, seconds in production)."""

    band_budget: Dict[PriorityClass, int] = field(
        default_factory=lambda: {
            PriorityClass.BATCH: 256,
            PriorityClass.FREE: 128,
        }
    )
    band_age_limit_s: Dict[PriorityClass, float] = field(
        default_factory=lambda: {
            PriorityClass.BATCH: 60.0,
            PriorityClass.FREE: 20.0,
        }
    )


@dataclass
class ShedTicket:
    """The resubmit ticket a shed pod leaves behind: everything a driver
    needs to retry the pod once the storm passes — the pod itself, its
    original arrival stamp (the north-star latency clock keeps running
    across a redemption), and why/where it was shed."""

    pod: object
    band: PriorityClass
    shard: int
    arrival: float
    shed_at: float
    reason: str = RejectReason.OVERLOAD_SHED.value
    detail: str = ""


class AdmissionController:
    """Fleet-shared admission policy + shed bookkeeping.

    One instance serves every shard's stream: per-shard DEPTH is the
    stream's own accounting (passed into :meth:`admit`), while the
    policy knobs, the brownout coupling, the shed tickets and the
    metrics are fleet-level here. Thread-safe — per-shard pump threads
    shed concurrently."""

    ADMIT = "admit"
    DEFER = "defer"
    SHED = "shed"

    def __init__(
        self,
        config: Optional[OverloadConfig] = None,
        brownout: Optional["BrownoutController"] = None,
        lifecycle=None,
        registry=None,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self.config = config or OverloadConfig()
        self.brownout = brownout
        self.lifecycle = lifecycle
        self.clock = clock
        self.registry = None
        self._shed_counter = None
        self._defer_counter = None
        #: decision observatory (obs.decisions.DecisionLedger). None =
        #: disabled; the record site is one attribute-is-None check.
        self.decisions = None
        self._decision_ticks = 0
        self._lock = threading.Lock()
        self._tickets: List[ShedTicket] = []  # guarded-by: self._lock
        #: band value -> pods shed, forever (the soak's PROD/MID-never-
        #: shed assert reads this)
        self.shed_counts: Dict[int, int] = {}  # guarded-by: self._lock
        self.deferred_total = 0  # guarded-by: self._lock
        if registry is not None:
            self.bind_registry(registry)

    def bind_registry(self, registry) -> None:
        """Adopt a metrics registry (first caller wins — the sharded
        fleet binds the first runtime's; the merged scrape carries it)."""
        if self.registry is not None:
            return
        self.registry = registry
        self._shed_counter = registry.get("overload_shed_total")
        self._defer_counter = registry.get("overload_deferred_total")

    def attach_decisions(self, ledger) -> None:
        """Wire the decision ledger (first caller wins — one fleet-level
        admission policy records into one ledger)."""
        if ledger is not None and self.decisions is None:
            self.decisions = ledger

    # ---- the submit-time verdict ----

    def admission_snapshot(self, pod, band_depth: int) -> dict:
        """The COMPLETE evidence :meth:`decide` reads for one arriving
        pod, as one pure dict (decision-observatory contract)."""
        band = pod.priority_class
        bo = self.brownout
        budget = self.config.band_budget.get(band)
        return {
            "band": band.name,
            "sheddable": band in SHEDDABLE_BANDS,
            "band_depth": int(band_depth),
            "budget": int(budget) if budget is not None else None,
            "brownout_level": bo.level if bo is not None else None,
            "brownout_sheds": bo.sheds(band) if bo is not None else False,
            "brownout_defers": (
                bo.defers(band) if bo is not None else False
            ),
        }

    @staticmethod
    def decide(inputs: dict):
        """Pure admission verdict from a snapshot — ``(action, state)``.
        Deterministic so a shadow or ``tools/decision_replay.py``
        re-deciding from RECORDED inputs reproduces the verdict."""
        if not inputs["sheddable"]:
            verdict = AdmissionController.ADMIT
        elif inputs["brownout_sheds"]:
            verdict = AdmissionController.SHED
        elif inputs["brownout_defers"]:
            verdict = AdmissionController.DEFER
        elif (
            inputs["budget"] is not None
            and inputs["band_depth"] >= inputs["budget"]
        ):
            verdict = AdmissionController.DEFER
        else:
            verdict = AdmissionController.ADMIT
        return {"verdict": verdict}, {}

    def admit(
        self, pod, band_depth: int, shard: Optional[int] = None
    ) -> str:
        """Admission verdict for one arriving pod given its band's
        current live-queue depth on the submitting shard: snapshot once,
        decide purely FROM the snapshot, record."""
        inputs = self.admission_snapshot(pod, band_depth)
        action, state = self.decide(inputs)
        dl = self.decisions
        if dl is not None:
            with self._lock:
                self._decision_ticks += 1
                tick = self._decision_ticks
            dl.record(
                "admission", tick, inputs, action, state, shard=shard
            )
        return action["verdict"]

    # ---- the sweep-time policy (deferred parking lot) ----

    def still_deferred(self, band: PriorityClass, live_depth: int) -> bool:
        """Whether a parked pod must stay parked: its band is brownout-
        deferred, or its band's live queue is still at budget."""
        bo = self.brownout
        if bo is not None and bo.defers(band):
            return True
        budget = self.config.band_budget.get(band)
        return budget is not None and live_depth >= budget

    def sheds_now(self, band: PriorityClass) -> bool:
        """Brownout L4: the band is shed outright (deferred AND fresh)."""
        bo = self.brownout
        return bo is not None and bo.sheds(band)

    def age_limit(self, band: PriorityClass) -> float:
        return self.config.band_age_limit_s.get(band, float("inf"))

    # ---- the terminal shed ----

    def shed(
        self,
        pod,
        shard: int,
        arrival: float,
        detail: str = "",
        reason: Optional[str] = None,
    ) -> ShedTicket:
        """The ONE canonical shed site (koordlint ``shed-paths`` pass):
        terminal ``shed`` lifecycle event, ``overload_shed_total{band}``
        metric, and the resubmit ticket. Every queue-drop path that
        shedding introduces funnels here. ``reason`` overrides the
        ticket's RejectReason value (gray-failure containment PR: a
        POISON_QUARANTINED shed rides the same funnel — its ticket is
        redeemable by a changed spec fingerprint, not by time)."""
        band = pod.priority_class
        now = self.clock()
        ticket = ShedTicket(
            pod=pod,
            band=band,
            shard=int(shard),
            arrival=arrival,
            shed_at=now,
            reason=reason or RejectReason.OVERLOAD_SHED.value,
            detail=detail,
        )
        lc = self.lifecycle
        if lc is not None:
            if not lc.seen(pod.meta.uid):
                lc.submitted(pod.meta.uid, t=arrival)
            lc.event(
                pod.meta.uid,
                "shed",
                shard=int(shard),
                detail=detail or band.name.lower(),
            )
        if self._shed_counter is not None:
            self._shed_counter.labels(band=band.name).inc()
        with self._lock:
            self._tickets.append(ticket)
            self.shed_counts[int(band)] = (
                self.shed_counts.get(int(band), 0) + 1
            )
        return ticket

    def note_deferred(self, band: PriorityClass) -> None:
        if self._defer_counter is not None:
            self._defer_counter.labels(band=band.name).inc()
        with self._lock:
            self.deferred_total += 1

    def take_tickets(self) -> List[ShedTicket]:
        """Drain the accumulated resubmit tickets (driver-owned retry:
        re-route/resubmit once the storm passes — the redeemed pod's
        timeline bridges ``shed`` with the fresh ``resubmit``/
        ``enqueue``)."""
        with self._lock:
            out, self._tickets = self._tickets, []
        return out

    def shed_total(self) -> int:
        with self._lock:
            return sum(self.shed_counts.values())


# ---------------------------------------------------------------------------
# The brownout ladder
# ---------------------------------------------------------------------------


class BrownoutController:
    """Monotonic, hysteresis-guarded degradation ladder over the fleet's
    SLO burn rates.

    Levels (each INCLUDES every lower level's degradation):

    =====  ======================================================
    L0     normal operation
    L1     pipeline depth capped at 1 (no deep speculation)
    L2     serial solve path + one batch-bucket degrade step
    L3     defer all BATCH/FREE admission (park, don't feed)
    L4     shed FREE outright (incoming and parked)
    =====  ======================================================

    The pressure signal is the same one the elastic
    :class:`~.elastic.TopologyController` scales on: the fleet-worst
    ``max(p99_latency, queue_age)`` burn rate. ``thresholds[i]`` is the
    burn at which level ``i+1`` becomes the target; the ladder moves ONE
    step per ``sustain`` consecutive hot ticks up and one step per
    ``cooldown`` consecutive cold ticks down — monotonic with
    hysteresis, never a jump, never a flap.

    Topology coordination: while an escalation is due from L0 and the
    topology controller still has scale-out budget (not cooling down
    from a transition, below ``max_shards``, no open transition), the
    ladder YIELDS for up to ``max_yield`` ticks — prefer a split that
    adds capacity over a brownout that sheds work; once the topology is
    inside its own transition cooldown (or out of budget), brown out.
    """

    L0, L1, L2, L3, L4 = range(5)
    MAX_LEVEL = L4

    def __init__(
        self,
        slo=None,
        shards: Optional[Callable[[], Sequence[int]]] = None,
        *,
        thresholds: Tuple[float, float, float, float] = (1.0, 2.0, 4.0, 8.0),
        sustain: int = 2,
        cooldown: int = 4,
        max_yield: Optional[int] = None,
        clock: Callable[[], float] = time.perf_counter,
        registry=None,
        topology=None,
        history: int = 64,
    ):
        if len(thresholds) != self.MAX_LEVEL or any(
            b >= a for a, b in zip(thresholds[1:], thresholds)
        ):
            raise ValueError(
                f"thresholds must be {self.MAX_LEVEL} ascending burns, "
                f"got {thresholds!r}"
            )
        self.slo = slo
        self.shards = shards
        self.thresholds = tuple(float(t) for t in thresholds)
        self.sustain = max(1, int(sustain))
        self.cooldown = max(1, int(cooldown))
        self.max_yield = self.sustain if max_yield is None else int(max_yield)
        self.clock = clock
        self.topology = topology
        #: the current ladder level — the ONE attribute every hot-path
        #: consumer reads (pipeline depth cap, serial gate, bucket
        #: degrade, admission defers/sheds)
        self.level = self.L0
        self._hot = 0
        self._cold = 0
        self._yields = 0
        self._ticks = 0
        self._level_since = self.clock()
        self._lock = threading.Lock()
        self._transitions: "deque[dict]" = deque(maxlen=int(history))  # guarded-by: self._lock
        self._healths: list = []
        #: decision observatory (obs.decisions.DecisionLedger) — the
        #: SINGLE attachment point: per-tick decisions record here and
        #: flight recorders attach THROUGH it (attach_flight), so a
        #: takeover adopts journaled controller evidence via one code
        #: path. None = disabled; every record site is one
        #: attribute-is-None check.
        self.decisions = None
        self._owns_ledger = False
        self.registry = None
        self._gauge = None
        self._trans_counter = None
        self.stats = {
            "escalations": 0,
            "deescalations": 0,
            "yielded_to_split": 0,
        }
        if registry is not None:
            self.bind_registry(registry)

    # ---- wiring ----

    def bind_registry(self, registry) -> None:
        if self.registry is not None:
            return
        self.registry = registry
        self._gauge = registry.get("brownout_level")
        self._trans_counter = registry.get("brownout_transitions_total")
        if self._gauge is not None:
            self._gauge.set(float(self.level))

    def attach_health(self, health) -> None:
        """Register a /healthz surface (one per scheduler runtime): the
        ``brownout`` row shows the live level; any level above L0 reads
        degraded — load balancers and operators see the storm."""
        if health is None or health in self._healths:
            return
        self._healths.append(health)
        health.set("brownout", self.level == self.L0, f"L{self.level}")

    def attach_decisions(self, ledger) -> None:
        """Wire the decision ledger. First EXTERNAL caller wins; an
        internally-created default (see :meth:`attach_flight`) is
        replaced and its flight attachments migrate, so the ledger a
        runtime provides is always the one that records."""
        if ledger is None or ledger is self.decisions:
            return
        if self.decisions is not None and self._owns_ledger:
            for fr in self.decisions._flights:
                ledger.attach_flight(fr)
        elif self.decisions is not None:
            return
        self.decisions = ledger
        self._owns_ledger = False

    def attach_flight(self, recorder) -> None:
        """Register a flight recorder to journal transitions into (the
        crash-surviving black box: a post-mortem must show WHEN the
        ladder moved relative to the cycles around it). Routed through
        the decision ledger's single attachment point — a ladder with no
        explicit ledger gets a default in-memory one so the journaled
        fields keep flowing unchanged."""
        if recorder is None:
            return
        dl = self.decisions
        if dl is None:
            from ..obs.decisions import DecisionLedger

            dl = DecisionLedger(clock=self.clock)
            self.decisions = dl
            self._owns_ledger = True
        dl.attach_flight(recorder)

    # ---- the pressure signal ----

    def pressure(self) -> float:
        """Fleet-worst placement burn — same signal, same accessor
        (``SloTracker.burn_rate``) the topology controller reads; new
        pressure signals join HERE, not as ad-hoc checks at call sites
        (ROADMAP standing rule)."""
        if self.slo is None:
            return 0.0
        shards = list(self.shards()) if self.shards is not None else None
        if shards is None:
            ev = self.slo.evaluate()
            shards = [int(s) for s in ev]
        worst = 0.0
        for s in shards:
            worst = max(
                worst,
                self.slo.burn_rate(s, "p99_latency"),
                self.slo.burn_rate(s, "queue_age"),
            )
        return worst

    def _target_for(self, burn: float) -> int:
        target = self.L0
        for i, thr in enumerate(self.thresholds):
            if burn >= thr:
                target = i + 1
        return target

    def _topology_can_relieve(self) -> bool:
        t = self.topology
        if t is None:
            return False
        return (not t.in_cooldown) and t.can_scale_out()

    # ---- the tick ----

    def snapshot(self) -> dict:
        """The COMPLETE evidence :meth:`decide` reads, as one pure dict
        (decision-observatory contract: the recorded inputs alone must
        reproduce the decision). The burn is recorded RAW — rounding it
        could flip a threshold comparison on replay."""
        return {
            "burn": self.pressure(),
            "level": self.level,
            "hot": self._hot,
            "cold": self._cold,
            "yields": self._yields,
            "thresholds": list(self.thresholds),
            "sustain": self.sustain,
            "cooldown": self.cooldown,
            "max_yield": self.max_yield,
            "topology_can_relieve": self._topology_can_relieve(),
        }

    @staticmethod
    def decide(inputs: dict):
        """Pure ladder decision from a snapshot — ``(action, state)``.
        Deterministic and side-effect-free (same-seed soak contract;
        shadow/replay re-deciding from RECORDED inputs must reproduce
        the acting move bit-exactly)."""
        level = int(inputs["level"])
        hot = int(inputs["hot"])
        cold = int(inputs["cold"])
        yields = int(inputs["yields"])
        burn = float(inputs["burn"])
        target = 0
        for i, thr in enumerate(inputs["thresholds"]):
            if burn >= thr:
                target = i + 1
        op = "hold"
        if target > level:
            hot += 1
            cold = 0
            if hot >= int(inputs["sustain"]):
                if (
                    level == BrownoutController.L0
                    and yields < int(inputs["max_yield"])
                    and inputs["topology_can_relieve"]
                ):
                    # capacity budget remains: give the topology
                    # controller a bounded window to split before the
                    # ladder starts degrading work
                    yields += 1
                    op = "yield"
                else:
                    hot = 0
                    yields = 0
                    op = "escalate"
                    level += 1
        elif target < level:
            cold += 1
            hot = 0
            yields = 0
            if cold >= int(inputs["cooldown"]):
                cold = 0
                op = "deescalate"
                level -= 1
        else:
            # pressure matched the level (or a split relieved it before
            # the ladder ever moved): the episode is over — the yield
            # budget renews for the NEXT storm, not just the next
            # transition
            hot = 0
            cold = 0
            yields = 0
        action = {"op": op, "to": level}
        state = {
            "level": level,
            "hot": hot,
            "cold": cold,
            "yields": yields,
            "target": target,
        }
        return action, state

    def tick(self, cycle: int = -1) -> Optional[dict]:
        """One evaluation: snapshot the evidence ONCE, decide purely
        FROM the snapshot (update the hot/cold streaks, move at most ONE
        level), apply, record. Returns the transition record when the
        level moved, else None."""
        self._ticks += 1
        inputs = self.snapshot()
        action, state = self.decide(inputs)
        self._hot = state["hot"]
        self._cold = state["cold"]
        self._yields = state["yields"]
        op = action["op"]
        rec = None
        if op == "yield":
            self.stats["yielded_to_split"] += 1
        elif op == "escalate":
            rec = self._set_level(
                self.level + 1, cycle, inputs["burn"], "escalate"
            )
        elif op == "deescalate":
            rec = self._set_level(
                self.level - 1, cycle, inputs["burn"], "deescalate"
            )
        dl = self.decisions
        if dl is not None:
            dl.record(
                "brownout",
                self._ticks if cycle < 0 else int(cycle),
                inputs,
                action,
                state,
                outcome={"burn": inputs["burn"]},
            )
        return rec

    def _set_level(
        self, level: int, cycle: int, burn: float, direction: str
    ) -> dict:
        prev = self.level
        now = self.clock()
        rec = {
            "t": now,
            "cycle": int(cycle),
            "from": prev,
            "to": int(level),
            "burn": round(float(burn), 4),
            "direction": direction,
        }
        self.level = int(level)
        self._yields = 0
        self._level_since = now
        self.stats[
            "escalations" if direction == "escalate" else "deescalations"
        ] += 1
        with self._lock:
            self._transitions.append(rec)
        if self._gauge is not None:
            self._gauge.set(float(self.level))
        if self._trans_counter is not None:
            self._trans_counter.labels(direction=direction).inc()
        for health in self._healths:
            health.set(
                "brownout",
                self.level == self.L0,
                f"L{self.level} (burn {burn:.2f})",
            )
        dl = self.decisions
        if dl is not None:
            # journaled beside the per-cycle records through the
            # ledger's single attachment point — never raises into the
            # control loop (FlightRecorder.record's own contract); the
            # field shapes predate the ledger and stay byte-compatible
            dl.flight_record(
                cycle=int(cycle),
                brownout={"from": prev, "to": self.level, "burn": burn},
                speculation="brownout",
            )
        return rec

    # ---- hot-path policy reads (one attribute check at each consumer) ----

    def pipeline_depth_cap(self) -> int:
        """L1+: no deep speculation — a storm's churn discards chained
        speculations anyway; stop paying for dispatches it will throw
        away."""
        return 1 if self.level >= self.L1 else 1 << 30

    def serial_only(self) -> bool:
        """L2+: the pipeline's ``brownout`` gate closes — cycles run the
        serial path (decision-identical by construction, no overlap)."""
        return self.level >= self.L2

    def bucket_degrade_steps(self) -> int:
        """L2+: one extra batch-bucket degrade step (smaller chunks keep
        per-cycle latency bounded under pressure, same mechanism as the
        deadline degrade)."""
        return 1 if self.level >= self.L2 else 0

    def defers(self, band: PriorityClass) -> bool:
        """L3+: BATCH/FREE admission parks instead of queueing."""
        return self.level >= self.L3 and band in SHEDDABLE_BANDS

    def sheds(self, band: PriorityClass) -> bool:
        """L4: FREE is shed outright."""
        return self.level >= self.L4 and band == PriorityClass.FREE

    # ---- introspection ----

    def transitions(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._transitions]

    def report(self) -> dict:
        return {
            "level": self.level,
            "level_name": f"L{self.level}",
            "since": self._level_since,
            "burn": round(self.pressure(), 4),
            "thresholds": list(self.thresholds),
            "sustain": self.sustain,
            "cooldown": self.cooldown,
            "stats": dict(self.stats),
            "transitions": self.transitions(),
        }

    def render(self) -> str:
        return json.dumps(self.report(), indent=1, sort_keys=True)


# ---------------------------------------------------------------------------
# Solver-channel circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Classic three-state breaker for the snapshot channel.

    CLOSED: calls pass; ``threshold`` CONSECUTIVE failures open it.
    OPEN: calls fail fast (``allow()`` is False) until ``cooldown_s``
    elapses, then one HALF_OPEN probe is admitted; its success closes
    the breaker, its failure re-opens (fresh cooldown). A persistent
    channel death thus costs one probe per cooldown window instead of a
    full retry-backoff ladder per call — the caller degrades to the
    host-reference path fast and stays there until the probe heals."""

    CLOSED, OPEN, HALF_OPEN = 0, 1, 2
    _NAMES = {0: "closed", 1: "open", 2: "half_open"}

    def __init__(
        self,
        threshold: int = 5,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        gauge=None,
    ):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.gauge = gauge
        self._lock = threading.Lock()
        self._state = self.CLOSED  # guarded-by: self._lock
        self._failures = 0  # guarded-by: self._lock
        self._opened_at = 0.0  # guarded-by: self._lock
        self._probing = False  # guarded-by: self._lock
        self.stats = {"trips": 0, "probes": 0, "closes": 0}
        #: decision observatory (obs.decisions.DecisionLedger). None =
        #: disabled; every record site is one attribute-is-None check.
        self.decisions = None
        self._decision_ticks = 0  # guarded-by: self._lock
        if gauge is not None:
            gauge.set(float(self.CLOSED))

    def attach_decisions(self, ledger) -> None:
        """Wire the decision ledger (first caller wins)."""
        if ledger is not None and self.decisions is None:
            self.decisions = ledger

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    @property
    def state_name(self) -> str:
        return self._NAMES[self.state]

    def _to(self, state: int) -> None:  # koordlint: holds=self._lock
        """Caller holds the lock."""
        self._state = state
        if self.gauge is not None:
            self.gauge.set(float(state))

    def _snapshot(self, op: str) -> dict:  # koordlint: holds=self._lock
        """Op-tagged evidence snapshot (caller holds the lock). The
        clock enters ONLY as the ``cooldown_elapsed`` boolean captured
        here, so :meth:`decide` stays pure and replayable."""
        return {
            "op": op,
            "state": self._NAMES[self._state],
            "probing": self._probing,
            "failures": self._failures,
            "threshold": self.threshold,
            "cooldown_elapsed": (
                self._state == self.OPEN
                and self.clock() - self._opened_at >= self.cooldown_s
            ),
        }

    @staticmethod
    def decide(inputs: dict):
        """Pure breaker transition from an op-tagged snapshot —
        ``(action, state)``. Deterministic: the snapshot already folded
        the clock into ``cooldown_elapsed``."""
        op = inputs["op"]
        st = str(inputs["state"])
        probing = bool(inputs["probing"])
        failures = int(inputs["failures"])
        reopen = False
        if op == "allow":
            probe = False
            if st == "closed":
                allowed = True
            elif st == "open":
                if inputs["cooldown_elapsed"]:
                    st = "half_open"
                    probing = True
                    probe = True
                    allowed = True
                else:
                    allowed = False
            else:
                # HALF_OPEN: the probe is in flight — admit nothing else
                if not probing:
                    probing = True
                    probe = True
                    allowed = True
                else:
                    allowed = False
            action = {
                "op": "allow" if allowed else "deny",
                "probe": probe,
            }
        elif op == "failure":
            probing = False
            if st == "half_open":
                # the probe failed: straight back to OPEN, fresh window
                st = "open"
                reopen = True
                action = {"op": "trip"}
            else:
                failures += 1
                if st == "closed" and failures >= int(
                    inputs["threshold"]
                ):
                    st = "open"
                    reopen = True
                    action = {"op": "trip"}
                else:
                    action = {"op": "count_failure"}
        else:  # success
            failures = 0
            probing = False
            if st != "closed":
                st = "closed"
                action = {"op": "close"}
            else:
                action = {"op": "ok"}
        state = {
            "state": st,
            "probing": probing,
            "failures": failures,
            "reopen": reopen,
        }
        return action, state

    _STATE_NUMS = {"closed": CLOSED, "open": OPEN, "half_open": HALF_OPEN}

    def _apply(self, action: dict, state: dict) -> None:  # koordlint: holds=self._lock
        """Apply a decided transition (caller holds the lock)."""
        num = self._STATE_NUMS[state["state"]]
        if num != self._state:
            self._to(num)
        self._failures = state["failures"]
        self._probing = state["probing"]
        if state["reopen"]:
            self._opened_at = self.clock()
        op = action["op"]
        if action.get("probe"):
            self.stats["probes"] += 1
        if op == "trip":
            self.stats["trips"] += 1
        elif op == "close":
            self.stats["closes"] += 1

    def _record(self, inputs: dict, action: dict, state: dict) -> None:
        dl = self.decisions
        if dl is not None:
            with self._lock:
                self._decision_ticks += 1
                tick = self._decision_ticks
            dl.record("breaker", tick, inputs, action, state)

    def allow(self) -> bool:
        """Whether a call may go out now. An OPEN breaker admits exactly
        ONE probe per cooldown window (HALF_OPEN); concurrent callers
        behind the probe fail fast until it settles. Snapshot once,
        decide purely FROM the snapshot, apply, record."""
        with self._lock:
            inputs = self._snapshot("allow")
            action, state = self.decide(inputs)
            self._apply(action, state)
        self._record(inputs, action, state)
        return action["op"] == "allow"

    def abort_probe(self) -> None:
        """An admitted call ended WITHOUT a channel verdict — e.g. a
        local fencing refusal before the wire, or a server-side fencing
        abort (neither says anything about channel health). Release the
        probe slot uncounted so the next ``allow()`` can re-probe;
        leaving ``_probing`` set would wedge a HALF_OPEN breaker
        forever (every later call fails fast, nothing ever settles)."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            inputs = self._snapshot("success")
            action, state = self.decide(inputs)
            self._apply(action, state)
        self._record(inputs, action, state)

    def record_failure(self) -> None:
        with self._lock:
            inputs = self._snapshot("failure")
            action, state = self.decide(inputs)
            self._apply(action, state)
        self._record(inputs, action, state)

    def report(self) -> dict:
        with self._lock:
            return {
                "state": self._NAMES[self._state],
                "failures": self._failures,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "stats": dict(self.stats),
            }
