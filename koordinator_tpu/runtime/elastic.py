"""Elastic shard topology (robustness tentpole): live shard SPLIT and
MERGE transactions, SLO-burn-driven scale-out/in, and cross-shard gang
scheduling.

The PR 6 control plane fixed the shard count at deploy time; this module
makes the partition a live, journaled quantity:

* :func:`split_shard` / :func:`merge_shards` — the topology
  transactions. Each is intent-before-mutate over the fabric's
  :class:`~.shards.ShardTopology` journal (generation-monotonic,
  fence-checked records): the donor(s) relinquish their cells through
  the ordinary step-down drain (queue continuity via
  ``extract_queued``/``resubmit``, trailing commits flushed through the
  revoked fence), the donors' journal LIVE SETS are re-homed into the
  child journals (so the children's first owners recover the parent's
  acknowledged world bit-exactly), and only then does the commit record
  swap the :class:`~.shards.ShardMap` cells — ClaimTable claims follow
  in the same commit step, tombstones stay (they are shard-less). The
  donor incarnation's OTHER shards serve throughout. The named chaos
  points ``shard.split_crash`` / ``shard.merge_crash`` fire between the
  re-home and the commit: the transaction journals a rollback and the
  parent generation stays active — never a half-owned range (the
  attempt's child ids stay burned so a stale child journal can never be
  mistaken for a live shard's).
* :class:`TopologyController` — the scale-out/in policy: it consumes
  the :class:`~..obs.slo.SloTracker` burn rates (until now only the
  descheduler read them) and splits a shard whose latency/queue-age
  budget has burned hot for ``sustain`` consecutive evaluations,
  re-merges sibling cells that have stayed cold, and spawns/retires
  scheduler incarnations to track the live shard count — with cooldown
  hysteresis so a burst cannot saw the topology back and forth.
* :class:`CrossShardGangCoordinator` — two-phase claim-then-commit for
  a gang whose feasible nodes SPAN shards (the PR 6 router routes gangs
  whole to a home shard, so such a gang was simply unplaceable):
  phase 1 takes all-or-nothing ClaimTable HOLDS on every member,
  phase 2 schedules each shard's members as a local sub-gang and either
  commits the holds into claims (every member bound) or aborts —
  releasing the holds entirely and unbinding any members that made it,
  so an abort leaves ZERO zombie holds and every member claimable for
  the retry. A claim phase that crashes mid-flight leaves zero holds by
  the ClaimTable's reload contract.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..chaos import NULL_INJECTOR
from ..core.journal import BindJournal, StaleEpochError


class TopologyChangeError(RuntimeError):
    """A topology transaction failed and was rolled back: the parent
    generation is still the active one (no range is half-owned)."""


# ---------------------------------------------------------------------------
# The split / merge transactions
# ---------------------------------------------------------------------------


def _relinquish_all(shard: int, incarnations, event: str, detail: str) -> None:
    """Step the shard's owner (whichever incarnation holds it) down so
    the journal re-home below sees a quiescent log. The surfaced queue
    rides the incarnation's ordinary handoff path — the driver re-routes
    it against whatever topology the transaction settles on."""
    for inc in incarnations:
        if not getattr(inc, "dead", False) and inc.owns(shard):
            inc.relinquish(shard, event=event, detail=detail)


def _rehome_journal(
    fabric,
    sources: Sequence[int],
    dest_of: Callable[[str], int],
    cycle: int,
    lifecycle=None,
    event: str = "shard_split",
    detail: str = "",
) -> Dict[str, int]:
    """Re-home every source shard's acknowledged live set into the
    destination journals (``dest_of(node) -> child shard``). Entries are
    re-journaled verbatim — exact NUMA/device holds, quota leaf and
    ``lc`` trace context included — so the child's takeover replay
    re-installs them bit-exactly, same as any PR 5 recovery. Returns
    ``uid -> destination`` (the ClaimTable re-home feed)."""
    moved: Dict[int, List[dict]] = {}
    dests: Dict[str, int] = {}
    for src in sources:
        rep = BindJournal(fabric.journal_stores[src], shard=src).replay()
        for uid, entry in rep.live.items():
            dest = int(dest_of(entry["node"]))
            moved.setdefault(dest, []).append(dict(entry))
            dests[uid] = dest
    for dest, entries in sorted(moved.items()):
        fabric.ensure_shard(dest)
        # the destination must be VIRGIN territory: a fence that ever
        # granted leadership means someone owns (or owned) this id and
        # a re-home would race its appends — epoch 0 is the never-
        # granted state, and check() raises on anything else
        fabric.fences[dest].check(0)
        BindJournal(
            fabric.journal_stores[dest], shard=dest
        ).append_bind(0, cycle, entries)
    if lifecycle is not None:
        for uid, dest in sorted(dests.items()):
            if not lifecycle.is_done(uid):
                # an acknowledged-but-unacked bind (a lost-ack window
                # crossing the transition) gets its bracket here; the
                # child's recovery `recover` event closes it. Terminal
                # timelines stay terminal — their story is over.
                lifecycle.event(
                    uid, event, shard=dest, detail=detail
                )
    return dests


def _rehome_claims(fabric, moves: Dict[str, int], void: List[int]) -> bool:
    """Best-effort claim re-home AFTER a committed transition. Failure
    is survivable — a claim stranded on a retired cell self-heals at
    the pod's next feed via ``ClaimTable.shard_live`` — so the error is
    swallowed and reported, never allowed to masquerade as a topology
    rollback."""
    try:
        fabric.claims.rehome(moves, void_shards=void)
        return True
    except Exception as exc:
        from ..obs.errors import report_exception

        report_exception("topology.claims_rehome", exc)
        return False


def split_shard(
    fabric,
    parent: int,
    incarnations: Sequence = (),
    chaos=None,
    lifecycle=None,
    cycle: int = -1,
) -> dict:
    """Split a hot shard's node range into two child shards, live.

    Transaction order (the invariants live in the order):

    1. journal the split INTENT (generation-monotonic, refuses a second
       open transition);
    2. the donor relinquishes the parent (queue surfaced with
       ``shard_split`` brackets, pipeline drained through the revoked
       fence) and the parent fence advances — a deposed straggler can
       cross no boundary;
    3. the parent journal's live set is re-homed into the child
       journals (children's fences must still be at epoch 0);
    4. ``shard.split_crash`` fires HERE when armed — the rollback path
       journals the abort, the parent stays the active cell, its owner
       re-elects, and the surfaced queue re-routes straight back to it;
    5. the COMMIT record swaps the map cells (routers now see the
       children) and the ClaimTable re-homes: bound pods' claims follow
       their node, a queued pod's claim on the retired parent is voided
       so it can re-claim wherever the new topology routes it.
    """
    chaos = chaos or NULL_INJECTOR
    topo = fabric.topology
    intent = topo.begin_split(parent)
    a, b = (int(i) for i in intent["children"])
    detail = f"gen{intent['gen']}:{parent}->{a}/{b}"
    try:
        fabric.ensure_shard(a)
        fabric.ensure_shard(b)
        _relinquish_all(parent, incarnations, "shard_split", detail)
        fabric.fences[parent].advance()
        moved = _rehome_journal(
            fabric,
            [parent],
            lambda node: fabric.shard_map.split_dest(parent, node, a, b),
            cycle,
            lifecycle=lifecycle,
            event="shard_split",
            detail=detail,
        )
        if chaos.fire("shard.split_crash"):
            raise TopologyChangeError(
                f"injected crash mid-split of shard {parent}"
            )
    except Exception as exc:
        topo.rollback(intent, reason=repr(exc))
        if isinstance(exc, TopologyChangeError):
            raise
        raise TopologyChangeError(
            f"split of shard {parent} failed: {exc!r}"
        ) from exc
    try:
        topo.commit(intent)
    except Exception as exc:
        # commit appends BEFORE swapping cells: a failed append leaves
        # the map untouched, so this is still a clean rollback
        topo.rollback(intent, reason=f"commit refused: {exc!r}")
        raise TopologyChangeError(
            f"split of shard {parent} could not commit: {exc!r}"
        ) from exc
    # past the commit the transition is FACT — a claims-journal failure
    # here must never masquerade as a rollback. Claims stranded on the
    # retired cell self-heal at their next feed (ClaimTable.shard_live),
    # so the re-home is best-effort convenience, not correctness.
    claims_rehomed = _rehome_claims(fabric, moved, [int(parent)])
    return {
        "op": "split",
        "gen": int(intent["gen"]),
        "parent": int(parent),
        "children": (a, b),
        "rehomed": len(moved),
        "claims_rehomed": claims_rehomed,
    }


def merge_shards(
    fabric,
    a: int,
    b: int,
    incarnations: Sequence = (),
    chaos=None,
    lifecycle=None,
    cycle: int = -1,
) -> dict:
    """Merge two cold SIBLING shards back into one (the inverse of
    :func:`split_shard`, same transaction discipline; the named chaos
    point is ``shard.merge_crash`` and its rollback re-opens BOTH
    donors' elections)."""
    chaos = chaos or NULL_INJECTOR
    topo = fabric.topology
    intent = topo.begin_merge(a, b)
    merged = int(intent["merged"])
    detail = f"gen{intent['gen']}:{a}+{b}->{merged}"
    try:
        fabric.ensure_shard(merged)
        for donor in (int(a), int(b)):
            fabric.ensure_shard(donor)
            _relinquish_all(donor, incarnations, "shard_merge", detail)
            fabric.fences[donor].advance()
        moved = _rehome_journal(
            fabric,
            [int(a), int(b)],
            lambda _node: merged,
            cycle,
            lifecycle=lifecycle,
            event="shard_merge",
            detail=detail,
        )
        if chaos.fire("shard.merge_crash"):
            raise TopologyChangeError(
                f"injected crash mid-merge of shards {a}+{b}"
            )
    except Exception as exc:
        topo.rollback(intent, reason=repr(exc))
        if isinstance(exc, TopologyChangeError):
            raise
        raise TopologyChangeError(
            f"merge of shards {a}+{b} failed: {exc!r}"
        ) from exc
    try:
        topo.commit(intent)
    except Exception as exc:
        topo.rollback(intent, reason=f"commit refused: {exc!r}")
        raise TopologyChangeError(
            f"merge of shards {a}+{b} could not commit: {exc!r}"
        ) from exc
    # committed: see split_shard — never roll back, claims self-heal
    claims_rehomed = _rehome_claims(fabric, moved, [int(a), int(b)])
    return {
        "op": "merge",
        "gen": int(intent["gen"]),
        "donors": (int(a), int(b)),
        "merged": merged,
        "rehomed": len(moved),
        "claims_rehomed": claims_rehomed,
    }


# ---------------------------------------------------------------------------
# SLO-burn-driven scale-out/in
# ---------------------------------------------------------------------------


class TopologyController:
    """Turns the PR 7 SLO layer's burn rates into topology actions.

    Policy, per :meth:`tick`:

    * a shard whose worst placement burn (max of ``p99_latency`` and
      ``queue_age``) exceeds ``split_burn`` for ``sustain`` consecutive
      ticks is SPLIT (hottest first, one transition per tick, cooldown
      between transitions);
    * a sibling cell pair whose burns have both stayed at or below
      ``merge_burn`` for ``sustain`` ticks is MERGED back;
    * ``spawn()`` / ``retire()`` callbacks (optional) keep the
      incarnation count tracking ``ceil(active / shards_per_inc)`` so a
      scale-out actually gains pump concurrency and a scale-in releases
      standbys.

    Degrades gracefully by construction: a split/merge that rolls back
    (chaos, crash, or a guard refusal) counts in ``stats["rollbacks"]``
    and the parent generation keeps serving. ``node_names`` (a callable
    returning the fleet's node names) guards against splits that would
    mint an EMPTY child — a shard with no nodes has no world for its
    owner to recover against."""

    def __init__(
        self,
        fabric,
        slo=None,
        incarnations: object = (),
        *,
        split_burn: float = 1.0,
        merge_burn: float = 0.05,
        sustain: int = 3,
        cooldown: int = 6,
        max_shards: int = 64,
        node_names: Optional[Callable[[], Sequence[str]]] = None,
        shards_per_incarnation: int = 2,
        min_incarnations: int = 1,
        spawn: Optional[Callable[[], object]] = None,
        retire: Optional[Callable[[], object]] = None,
        chaos=None,
        lifecycle=None,
        freshness=None,
    ):
        self.fabric = fabric
        self.slo = slo
        self._incarnations = incarnations
        self.split_burn = float(split_burn)
        self.merge_burn = float(merge_burn)
        self.sustain = int(sustain)
        self.cooldown = int(cooldown)
        self.max_shards = int(max_shards)
        self.node_names = node_names
        self.shards_per_incarnation = max(1, int(shards_per_incarnation))
        self.min_incarnations = int(min_incarnations)
        self.spawn = spawn
        self.retire = retire
        self.chaos = chaos or NULL_INJECTOR
        self.lifecycle = lifecycle
        #: gray-failure containment: zero-arg callable (the staleness
        #: watchdog's ``stale``) folded into the snapshot — a topology
        #: split/merge is evidence-hungry (it re-homes real queues on
        #: burn-rate evidence) and must refuse on stale informer data
        self.freshness = freshness
        self._hot: Dict[int, int] = {}
        self._cold: Dict[int, int] = {}
        self._ticks = 0
        self._last_change = -(10**9)
        #: decision observatory (obs.decisions.DecisionLedger). None =
        #: disabled; the record site is one attribute-is-None check.
        self.decisions = None
        self.stats = {
            "splits": 0,
            "merges": 0,
            "rollbacks": 0,
            "skipped": 0,
            "spawned": 0,
            "retired": 0,
        }

    # ---- plumbing ----

    def _live(self) -> List:
        incs = self._incarnations
        if callable(incs):
            incs = incs()
        return [i for i in incs if not getattr(i, "dead", False)]

    def shard_burn(self, shard: int) -> float:
        """The shard's worst PLACEMENT burn rate — the signal that says
        "this range needs more scheduler", which recovery burn does not.
        Also the router's spill view (``ShardRouter(burn_of=...)``) and
        the brownout ladder's per-shard input (overload-control PR)."""
        if self.slo is None:
            return 0.0
        return max(
            self.slo.burn_rate(shard, "p99_latency"),
            self.slo.burn_rate(shard, "queue_age"),
        )

    @property
    def in_cooldown(self) -> bool:
        """True while the controller is inside the post-transition
        cooldown window — the window the brownout ladder browns out in
        (overload-control PR): capacity is NOT coming, degrade instead."""
        return self._ticks - self._last_change < self.cooldown

    def can_scale_out(self) -> bool:
        """Whether a split could still relieve pressure: shards below
        the cap, a node-name view to pick a candidate from, and no
        transition already open. The brownout ladder YIELDS escalation
        while this holds — prefer a split that adds capacity over a
        brownout that sheds work."""
        m = self.fabric.shard_map
        if len(m.active_shards()) >= self.max_shards:
            return False
        if self.node_names is None:
            return False
        topo = getattr(self.fabric, "topology", None)
        if topo is not None and topo.open_transition() is not None:
            return False
        return True

    def _children_nonempty(
        self, shard: int, names: Optional[Sequence[str]] = None
    ) -> bool:
        """A split that would mint an empty child is refused up front —
        deterministic hash partitioning makes this a property of the
        node-name set, so check it before burning a generation.
        ``names`` (the shard's own nodes, when the caller already
        partitioned) skips re-hashing the whole fleet."""
        if self.node_names is None:
            return True
        m = self.fabric.shard_map
        if names is None:
            names = [
                n for n in self.node_names() if m.shard_of_node(n) == shard
            ]
        if not names:
            return False
        sides = {m.split_dest(shard, n, 0, 1) for n in names}
        return sides == {0, 1}

    def pick_split_candidate(self) -> Optional[int]:
        """The active shard owning the most nodes whose split yields two
        non-empty children (ties break on shard id — deterministic, so
        the seeded soak schedules the same split every run)."""
        if self.node_names is None:
            return None
        part = self.fabric.shard_map.partition(list(self.node_names()))
        for shard in sorted(part, key=lambda s: (-len(part[s]), s)):
            if self._children_nonempty(shard, names=part[shard]):
                return shard
        return None

    # ---- the actions ----

    def split(self, shard: int, cycle: int = -1) -> Optional[dict]:
        if not self._children_nonempty(shard):
            self.stats["skipped"] += 1
            return None
        try:
            out = split_shard(
                self.fabric,
                shard,
                incarnations=self._live(),
                chaos=self.chaos,
                lifecycle=self.lifecycle,
                cycle=cycle,
            )
        except TopologyChangeError:
            self.stats["rollbacks"] += 1
            self._last_change = self._ticks
            return None
        self.stats["splits"] += 1
        self._last_change = self._ticks
        self._hot.pop(shard, None)
        self._cold.pop(shard, None)
        return out

    def merge(self, a: int, b: int, cycle: int = -1) -> Optional[dict]:
        try:
            out = merge_shards(
                self.fabric,
                a,
                b,
                incarnations=self._live(),
                chaos=self.chaos,
                lifecycle=self.lifecycle,
                cycle=cycle,
            )
        except TopologyChangeError:
            self.stats["rollbacks"] += 1
            self._last_change = self._ticks
            return None
        self.stats["merges"] += 1
        self._last_change = self._ticks
        for s in (a, b):
            self._hot.pop(s, None)
            self._cold.pop(s, None)
        return out

    def attach_decisions(self, ledger) -> None:
        """Wire the decision ledger (first caller wins)."""
        if ledger is not None and self.decisions is None:
            self.decisions = ledger

    def snapshot(self) -> dict:
        """The COMPLETE evidence :meth:`decide` reads, as one pure dict
        (decision-observatory contract). Burns are recorded RAW —
        rounding could flip a threshold comparison on replay; the hot/
        cold streaks are the PRE-tick values (decide advances them)."""
        active = [int(s) for s in self.fabric.shard_map.active_shards()]
        return {
            "active": active,
            "burns": {int(s): self.shard_burn(s) for s in active},
            "hot": dict(self._hot),
            "cold": dict(self._cold),
            "in_cooldown": self.in_cooldown,
            "siblings": [
                [int(a), int(b)]
                for a, b in self.fabric.shard_map.siblings()
            ],
            "max_shards": self.max_shards,
            "sustain": self.sustain,
            "split_burn": self.split_burn,
            "merge_burn": self.merge_burn,
            # staleness-snapshot rule: decide() reads the freshness
            # verdict FROM the snapshot, never live — replay sees the
            # same refusal the acting controller made
            "stale": (
                bool(self.freshness())
                if self.freshness is not None
                else False
            ),
        }

    @staticmethod
    def decide(inputs: dict):
        """Pure topology decision from a snapshot — ``(action, state)``.

        Deterministic and side-effect-free. Keys are coerced back to
        int because a snapshot replayed through the journal store (or
        ``tools/decision_replay.py``) comes back JSON-shaped with
        string dict keys."""
        active = [int(s) for s in inputs["active"]]
        burns = {int(k): float(v) for k, v in inputs["burns"].items()}
        hot = {int(k): int(v) for k, v in inputs["hot"].items()}
        cold = {int(k): int(v) for k, v in inputs["cold"].items()}
        sustain = int(inputs["sustain"])
        for s in active:
            if burns[s] > float(inputs["split_burn"]):
                hot[s] = hot.get(s, 0) + 1
                cold.pop(s, None)
            elif burns[s] <= float(inputs["merge_burn"]):
                cold[s] = cold.get(s, 0) + 1
                hot.pop(s, None)
            else:
                hot.pop(s, None)
                cold.pop(s, None)
        action = {"op": "none"}
        # stale informer evidence: burns computed over a silent-stalled
        # snapshot must not re-home queues — streaks still advance (the
        # evidence MAY be real; resuming events will confirm) but the
        # topology action itself refuses until freshness returns.
        # .get() keeps pre-containment recorded snapshots replayable.
        if bool(inputs.get("stale", False)):
            state = {"hot": hot, "cold": cold}
            return {"op": "none", "stale_refused": True}, state
        if not inputs["in_cooldown"]:
            hot_list = sorted(
                (s for s in active if hot.get(s, 0) >= sustain),
                key=lambda s: (-burns[s], s),
            )
            if hot_list and len(active) < int(inputs["max_shards"]):
                action = {"op": "split", "shard": hot_list[0]}
            elif not hot_list:
                for a, b in inputs["siblings"]:
                    a, b = int(a), int(b)
                    if (
                        cold.get(a, 0) >= sustain
                        and cold.get(b, 0) >= sustain
                    ):
                        action = {"op": "merge", "pair": [a, b]}
                        break
        state = {"hot": hot, "cold": cold}
        return action, state

    def tick(self, cycle: int = -1) -> List[dict]:
        """One burn-driven evaluation: snapshot the evidence ONCE,
        decide purely FROM the snapshot (update hot/cold streaks, pick
        at most one cooldown-gated topology action), apply, record,
        then true up the incarnation count. Returns the actions taken."""
        self._ticks += 1
        inputs = self.snapshot()
        action, state = self.decide(inputs)
        self._hot = dict(state["hot"])
        self._cold = dict(state["cold"])
        actions: List[dict] = []
        if action["op"] == "split":
            out = self.split(int(action["shard"]), cycle=cycle)
            if out is not None:
                actions.append(out)
        elif action["op"] == "merge":
            a, b = action["pair"]
            out = self.merge(int(a), int(b), cycle=cycle)
            if out is not None:
                actions.append(out)
        # incarnation scale-out/in tracks the live shard count
        live = self._live()
        target = max(
            self.min_incarnations,
            math.ceil(
                len(self.fabric.shard_map.active_shards())
                / self.shards_per_incarnation
            ),
        )
        if self.spawn is not None and len(live) < target:
            self.spawn()
            self.stats["spawned"] += 1
            actions.append({"op": "spawn", "target": target})
        elif self.retire is not None and len(live) > target:
            self.retire()
            self.stats["retired"] += 1
            actions.append({"op": "retire", "target": target})
        dl = self.decisions
        if dl is not None:
            dl.record(
                "topology",
                self._ticks if cycle < 0 else int(cycle),
                inputs,
                action,
                state,
                outcome={"applied": len(actions)},
            )
        return actions


# ---------------------------------------------------------------------------
# Cross-shard gang scheduling (two-phase claim-then-commit)
# ---------------------------------------------------------------------------


@dataclass
class GangTicket:
    """One cross-shard gang placement attempt in flight."""

    gang: str
    attempt_id: str
    #: uid -> the shard scheduled to bind it
    members: Dict[str, int]
    pods: Dict[str, object]
    #: uid -> node (bound) | None (terminally unschedulable)
    decided: Dict[str, Optional[str]] = field(default_factory=dict)
    #: uid -> {annotation key: original value | None} — what the
    #: sub-gang rewrite changed, so an abort can restore the pods to
    #: their pre-attempt shape (a retry must see the ORIGINAL gang)
    saved_annotations: Dict[str, Dict[str, Optional[str]]] = field(
        default_factory=dict
    )
    committed: bool = False
    aborted: bool = False

    def complete(self) -> bool:
        return len(self.decided) == len(self.members)


class CrossShardGangCoordinator:
    """All-or-nothing placement for a gang whose members span shards.

    ``owner_of(shard)`` resolves the incarnation currently owning a
    shard (None when ownerless — the attempt is refused with zero
    holds). The driver pumps its shards as usual, reports each member's
    decision via :meth:`note`, and calls :meth:`finish` once the ticket
    completes; ``finish`` commits the holds (all bound) or aborts —
    unbinding any members that made it via the caller's ``unbind``
    callback (the bind-API delete, which releases snapshot/journal
    charges through the ordinary informer fan-out) and dropping every
    hold so nothing is left zombie-claimed."""

    def __init__(self, fabric, router, owner_of, lifecycle=None):
        self.fabric = fabric
        self.router = router
        self.owner_of = owner_of
        self.lifecycle = lifecycle
        self._attempts = 0
        self.stats = {
            "placed": 0,
            "aborted": 0,
            "refused": 0,
            "unbound": 0,
        }

    def begin(self, pods: Sequence) -> Optional[GangTicket]:
        """Phase 1: route the members, take all-or-nothing holds, and
        submit each shard's members as a LOCAL sub-gang (min = that
        shard's member count, so the in-shard Permit machinery keeps the
        local subset atomic). Returns None — with zero holds — when a
        member's shard is ownerless or any hold is refused."""
        from ..scheduler.plugins.coscheduling import gang_key_of

        gang = gang_key_of(pods[0]) or f"anon/{pods[0].meta.uid}"
        members = {p.meta.uid: self.router.route(p) for p in pods}
        owners = {}
        epochs = {}
        for shard in sorted(set(members.values())):
            owner = self.owner_of(shard)
            rt = owner.runtime(shard) if owner is not None else None
            if rt is None:
                # ownerless — or the owner stepped down between the
                # lookup and this read (the runtime is the epoch's
                # source of truth, so read it exactly once)
                self.stats["refused"] += 1
                return None
            owners[shard] = owner
            epochs[shard] = rt.sched._fence_epoch
        self._attempts += 1
        attempt_id = f"xsgang:{gang}#{self._attempts}"
        try:
            won = self.fabric.claims.gang_prepare(
                attempt_id, members, epochs
            )
        except StaleEpochError:
            self.stats["refused"] += 1
            return None
        if not won:
            self.stats["refused"] += 1
            return None
        ticket = GangTicket(
            gang=gang,
            attempt_id=attempt_id,
            members=dict(members),
            pods={p.meta.uid: p for p in pods},
        )
        try:
            by_shard: Dict[int, List] = {}
            for p in pods:
                by_shard.setdefault(members[p.meta.uid], []).append(p)
            submit_failed = False
            for shard, group in sorted(by_shard.items()):
                if submit_failed:
                    # an earlier shard refused: the gang is already
                    # doomed — don't enqueue more members
                    for p in group:
                        ticket.decided[p.meta.uid] = None
                    continue
                self._rewrite_subgang(gang, shard, group, ticket)
                for p in group:
                    if submit_failed or not owners[shard].submit(shard, p):
                        # the owner lost the shard between the
                        # ownership check and the submit (lease lapse /
                        # step-down): mark this member — and every
                        # not-yet-submitted one — terminally undecided
                        # so the ticket still COMPLETES and finish()
                        # aborts through the ordinary path, unbinding
                        # whatever the already-submitted members do
                        # bind. Zero zombie holds either way.
                        submit_failed = True
                        ticket.decided[p.meta.uid] = None
        except Exception:
            # the claim phase crashed mid-submit: zero holds survive,
            # and the pods go back to their original gang shape
            self.fabric.claims.gang_abort(attempt_id)
            self._restore_subgang(ticket)
            self.stats["refused"] += 1
            raise
        if submit_failed and ticket.complete():
            # NOTHING was submitted anywhere — abort immediately (no
            # decisions will ever arrive to drive finish())
            self.fabric.claims.gang_abort(attempt_id)
            self._restore_subgang(ticket)
            ticket.aborted = True
            self.stats["refused"] += 1
            return None
        return ticket

    @staticmethod
    def _rewritten_keys():
        from ..api import extension as ext

        return (
            ext.ANNOTATION_GANG_NAME,
            ext.ANNOTATION_GANG_MIN_AVAILABLE,
            ext.ANNOTATION_GANG_TOTAL_NUM,
            ext.ANNOTATION_GANG_GROUPS,
        )

    @classmethod
    def _rewrite_subgang(
        cls, gang: str, shard: int, group: Sequence, ticket: GangTicket
    ) -> None:
        """Rewrite the members of one shard into a shard-local sub-gang
        sized to exactly the local member count — the shard's own
        PodGroupManager then enforces local atomicity while the
        cross-shard holds enforce global atomicity. Everything touched
        is SAVED on the ticket so an abort restores the pods to their
        original gang shape (a retry must route and size by the
        original gang, not a first attempt's sub-group residue)."""
        from ..api import extension as ext

        bare = gang.split("/", 1)[-1]
        for pod in group:
            ann = pod.meta.annotations
            ticket.saved_annotations[pod.meta.uid] = {
                k: ann.get(k) for k in cls._rewritten_keys()
            }
            ann[ext.ANNOTATION_GANG_NAME] = f"{bare}-xs{shard}"
            ann[ext.ANNOTATION_GANG_MIN_AVAILABLE] = str(len(group))
            ann[ext.ANNOTATION_GANG_TOTAL_NUM] = str(len(group))
            ann.pop(ext.ANNOTATION_GANG_GROUPS, None)
            try:
                del pod._gang_key  # bust the memoized key
            except AttributeError:
                pass

    @classmethod
    def _restore_subgang(cls, ticket: GangTicket) -> None:
        """Abort path: put every rewritten member back into its
        original gang shape so the retry sees the true gang."""
        for uid, saved in ticket.saved_annotations.items():
            pod = ticket.pods[uid]
            for key, value in saved.items():
                if value is None:
                    pod.meta.annotations.pop(key, None)
                else:
                    pod.meta.annotations[key] = value
            try:
                del pod._gang_key
            except AttributeError:
                pass

    def note(
        self, ticket: GangTicket, uid: str, node: Optional[str]
    ) -> Optional[bool]:
        """Record one member's decision. Returns None while incomplete,
        else True (every member bound) / False (abort required)."""
        if uid in ticket.members:
            ticket.decided[uid] = node
        if not ticket.complete():
            return None
        return all(n is not None for n in ticket.decided.values())

    def finish(self, ticket: GangTicket, unbind=None) -> bool:
        """Phase 2 close-out: commit when every member bound, else
        abort — unbind the partial placements and drop every hold."""
        if ticket.committed or ticket.aborted:
            return ticket.committed
        if all(n is not None for n in ticket.decided.values()) and (
            ticket.complete()
        ):
            self.fabric.claims.gang_commit(ticket.attempt_id)
            ticket.committed = True
            self.stats["placed"] += 1
            return True
        for uid, node in sorted(ticket.decided.items()):
            if node is not None and unbind is not None:
                unbind(ticket.pods[uid], ticket.members[uid], node)
                self.stats["unbound"] += 1
        self.fabric.claims.gang_abort(ticket.attempt_id)
        # a topology transition mid-attempt may have voided a member's
        # hold and let its feed re-claim plainly — drop any such claim
        # (tombstone-free) so every aborted member is fully claimable
        self.fabric.claims.void_claims(sorted(ticket.members))
        self._restore_subgang(ticket)
        ticket.aborted = True
        self.stats["aborted"] += 1
        return False
