"""Descheduler framework: plugin API, profiles, loop, dry-run.

Rebuild of ``pkg/descheduler/framework/`` (plugin contracts
``types.go:78-103``), ``framework/runtime/`` (registry + profiles,
dry-run at ``framework/runtime:103-105``), and the top-level loop
(``descheduler.go:243-283``): every interval, run each profile's
Deschedule plugins then Balance plugins over the node set; evictions
flow through the profile's Evictor, which Filter plugins and the
evictability policy gate, and which dry-run mode turns into a recorder.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Protocol, Sequence

from ..api.types import Node, Pod
from .evictor import Evictor, PodEvictionPolicy


class DeschedulePlugin(Protocol):
    """Strategy evicting by per-pod policy violations (types.go:78-86)."""

    name: str

    def deschedule(self, ctx: "FrameworkContext") -> int: ...


class BalancePlugin(Protocol):
    """Strategy redistributing load across nodes (types.go:88-95)."""

    name: str

    def balance(self, ctx: "FrameworkContext") -> int: ...


FilterFn = Callable[[Pod], bool]


@dataclasses.dataclass
class EvictionRecord:
    pod: Pod
    reason: str
    plugin: str
    executed: bool


@dataclasses.dataclass
class FrameworkContext:
    """What plugins see each round: the node/pod inventory + the evict
    entry point (the framework handle of the reference)."""

    nodes: Sequence[Node]
    pods: Sequence[Pod]
    evict: Callable[[Pod, str, str], bool]     # (pod, reason, plugin)


class Profile:
    """One descheduling profile: ordered plugin lists + an evictor chain
    (``framework/runtime/profile.go``)."""

    def __init__(
        self,
        name: str,
        deschedule_plugins: Sequence[DeschedulePlugin] = (),
        balance_plugins: Sequence[BalancePlugin] = (),
        evictor: Optional[Evictor] = None,
        policy: Optional[PodEvictionPolicy] = None,
        filters: Sequence[FilterFn] = (),
        dry_run: bool = False,
        max_evictions_per_round: int = 0,
        tracer=None,
    ):
        from ..obs import NULL_TRACER

        self.name = name
        self.deschedule_plugins = list(deschedule_plugins)
        self.balance_plugins = list(balance_plugins)
        self.evictor = evictor
        self.policy = policy or PodEvictionPolicy()
        self.filters = list(filters)
        self.dry_run = dry_run
        self.max_evictions_per_round = max_evictions_per_round
        self.tracer = tracer or NULL_TRACER
        self.records: List[EvictionRecord] = []
        self._round_evictions = 0
        self._round_seq = 0

    def _evict(self, pod: Pod, reason: str, plugin: str) -> bool:
        if (
            self.max_evictions_per_round
            and self._round_evictions >= self.max_evictions_per_round
        ):
            return False
        if not self.policy.evictable(pod):
            return False
        for f in self.filters:
            if not f(pod):
                return False
        executed = False
        if not self.dry_run and self.evictor is not None:
            executed = self.evictor.evict(pod, reason)
        self.records.append(
            EvictionRecord(pod=pod, reason=reason, plugin=plugin, executed=executed)
        )
        if executed or self.dry_run:
            self._round_evictions += 1
        return executed or self.dry_run

    def run_once(self, nodes: Sequence[Node], pods: Sequence[Pod]) -> Dict[str, int]:
        """One descheduler round: Deschedule plugins then Balance plugins
        (descheduler.go:261-283 deschedulerOnce ordering); every plugin
        run gets a child span under the round span, tagged with the
        per-profile round id and the plugin's eviction count."""
        self._round_evictions = 0
        self._round_seq += 1
        rid = self._round_seq
        tr = self.tracer
        ctx = FrameworkContext(nodes=nodes, pods=pods, evict=self._evict)
        counts: Dict[str, int] = {}
        with tr.span(
            f"round:{self.name}", cat="descheduler", cycle=rid,
            nodes=len(nodes), pods=len(pods),
        ):
            for plugin in self.deschedule_plugins:
                with tr.span(
                    f"plugin:{plugin.name}:deschedule",
                    cat="descheduler",
                    cycle=rid,
                ) as sp:
                    counts[plugin.name] = plugin.deschedule(ctx)
                    sp.set(evicted=counts[plugin.name])
            for plugin in self.balance_plugins:
                with tr.span(
                    f"plugin:{plugin.name}:balance",
                    cat="descheduler",
                    cycle=rid,
                ) as sp:
                    counts[plugin.name] = plugin.balance(ctx)
                    sp.set(evicted=counts[plugin.name])
        return counts


class Registry:
    """Plugin factory registry (``framework/runtime/registry.go``)."""

    def __init__(self) -> None:
        self._factories: Dict[str, Callable[..., object]] = {}

    def register(self, name: str, factory: Callable[..., object]) -> None:
        if name in self._factories:
            raise ValueError(f"plugin {name} already registered")
        self._factories[name] = factory

    def build(self, name: str, *args, **kwargs) -> object:
        if name not in self._factories:
            raise KeyError(f"unknown descheduler plugin {name}")
        return self._factories[name](*args, **kwargs)

    def names(self) -> List[str]:
        return sorted(self._factories)


class Descheduler:
    """The loop owner: profiles run in order every interval
    (``descheduler.go:243-283``; time is injected — the reference uses
    ``wait.Until``)."""

    def __init__(self, profiles: Sequence[Profile], interval_s: float = 120.0):
        self.profiles = list(profiles)
        self.interval_s = interval_s
        self.rounds = 0

    def run_once(
        self, nodes: Sequence[Node], pods: Sequence[Pod]
    ) -> Dict[str, Dict[str, int]]:
        self.rounds += 1
        return {p.name: p.run_once(nodes, pods) for p in self.profiles}
