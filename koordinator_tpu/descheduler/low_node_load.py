"""LowNodeLoad descheduler plugin: utilization-driven rebalancing.

Rebuild of ``pkg/descheduler/framework/plugins/loadaware/low_node_load.go:
137-265`` + ``utilization_util.go``: nodes are classified low/high against
NodeMetric utilization thresholds (total and prod tiers), a debouncing
anomaly detector (``low_node_load.go:286-312``) requires a node to stay
overutilized for N consecutive rounds before action, then victims are
picked from high nodes — lowest priority band / QoS first, highest usage
first — but only if they fit on some underutilized node (checked with the
same fit masks the scheduler uses, SURVEY §7 step 7: "reusing the same
cost tensor for eviction selection").

Classification and target-fit checks are vectorized over the node axis;
victim ordering is a host-side sort over the (small) candidate set.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..api import extension as ext
from ..api.types import Pod
from ..core.snapshot import ClusterSnapshot


@dataclasses.dataclass
class LowNodeLoadArgs:
    """Thresholds in percent of allocatable (reference LowNodeLoadArgs)."""

    high_thresholds: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: {ext.RES_CPU: 65.0, ext.RES_MEMORY: 80.0}
    )
    low_thresholds: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: {ext.RES_CPU: 45.0, ext.RES_MEMORY: 60.0}
    )
    prod_high_thresholds: Mapping[str, float] = dataclasses.field(default_factory=dict)
    #: deviation mode (reference UseDeviationThresholds / getNodeThresholds):
    #: thresholds become offsets around the cluster-average utilization —
    #: low = avg − low_thresholds, high = avg + high_thresholds, clamped to
    #: [0, 100]. Spot-checks outliers instead of absolute levels.
    use_deviation_thresholds: bool = False
    #: consecutive overutilized rounds before a node is actionable
    #: (anomaly detector debounce, low_node_load.go:286-312)
    anomaly_condition_count: int = 2
    #: stop evicting once the node is projected below high thresholds
    target_margin_percent: float = 5.0
    max_evictions_per_node: int = 5
    #: per-resource victim-sort weights (reference ResourceWeights — both
    #: 1 by default); only dims the source node actually overuses count
    #: (``utilization_util.go:700-727`` sortPodsOnOneOverloadedNode)
    resource_weights: Mapping[str, float] = dataclasses.field(default_factory=dict)
    #: check victims fit some low node before evicting (reference NodeFit,
    #: default true)
    node_fit: bool = True
    #: SLO-driven actuation (distributed-observability follow-on, flag
    #: OFF by default): when the plugin is wired with an
    #: ``obs.slo.SloTracker`` and this flag is on, a shard burning its
    #: placement-latency/queue-age error budget TIGHTENS the high
    #: thresholds by the burn factor — overloaded nodes become
    #: actionable sooner, raising migration pressure exactly while the
    #: SLO is being spent. A healthy shard (burn ≤ 1) changes nothing.
    slo_pressure: bool = False
    #: cap on the threshold-tightening factor (burn rates are unbounded;
    #: pressure must not collapse the thresholds to zero)
    slo_pressure_cap: float = 4.0


@dataclasses.dataclass
class NodePool:
    """One pool of a multi-pool config (reference LowNodeLoadNodePool,
    ``types_loadaware.go:97-122``): nodes matching ``node_selector`` get
    this pool's thresholds/weights; classification and victim selection
    run per pool."""

    name: str
    node_selector: Mapping[str, str]
    args: LowNodeLoadArgs


@dataclasses.dataclass
class NodeClassification:
    low: np.ndarray     # [N] bool
    high: np.ndarray    # [N] bool (debounced)
    raw_high: np.ndarray  # [N] bool (before debounce)
    utilization: np.ndarray  # [N, D] percent
    #: effective high thresholds in percent ([D]) — deviation mode turns
    #: the configured offsets into absolute levels around the mean, and
    #: victim selection must use the SAME levels classification did
    hi_eff: np.ndarray = None


class LowNodeLoad:
    def __init__(
        self,
        snapshot: ClusterSnapshot,
        args: Optional[LowNodeLoadArgs] = None,
        slo=None,
        shard: int = 0,
    ):
        self.snapshot = snapshot
        self.args = args or LowNodeLoadArgs()
        #: optional obs.slo.SloTracker + the shard this plugin reba-
        #: lances for — the first consumer of the /slo layer (burn rate
        #: → migration pressure, behind args.slo_pressure)
        self.slo = slo
        self.shard = int(shard)
        self._over_counts: Dict[int, int] = {}
        self._last_cls: Optional[NodeClassification] = None

    def slo_pressure_factor(self) -> float:
        """Threshold-tightening factor from the shard's SLO burn rate:
        1.0 while healthy (or the flag/tracker is absent); the worst
        latency/queue-age burn, capped, while the error budget burns
        faster than it accrues."""
        if not self.args.slo_pressure or self.slo is None:
            return 1.0
        rows = self.slo.evaluate().get(str(self.shard), {})
        burn = max(
            (
                rows.get(name, {}).get("burn_rate", 0.0)
                for name in ("p99_latency", "queue_age")
            ),
            default=0.0,
        )
        if burn <= 1.0:
            return 1.0
        return float(min(burn, self.args.slo_pressure_cap))

    def _vec(self, table: Mapping[str, float]) -> np.ndarray:
        return np.array(
            [float(table.get(r, 0.0)) for r in self.snapshot.config.resources],
            np.float32,
        )

    def classify(
        self,
        update_debounce: bool = True,
        node_mask: Optional[np.ndarray] = None,
    ) -> NodeClassification:
        """Classify nodes; ``update_debounce=True`` advances the anomaly
        counters (call once per descheduling round). ``peek`` via
        update_debounce=False is side-effect-free. ``node_mask`` restricts
        the pool of nodes considered (NodePool selector)."""
        na = self.snapshot.nodes
        alloc = np.maximum(na.allocatable, 1e-9)
        used = np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
        util = used * 100.0 / alloc
        hi = self._vec(self.args.high_thresholds)
        lo = self._vec(self.args.low_thresholds)
        active = na.schedulable & na.metric_fresh
        if node_mask is not None:
            active = active & node_mask
        hi_on, lo_on = hi > 0, lo > 0
        hi_eff = hi[None, :]
        lo_eff = lo[None, :]
        if self.args.use_deviation_thresholds and active.any():
            # calcAverageResourceUsagePercent over active nodes; offsets
            # around it, normalized to [0, 100]
            avg = util[active].mean(axis=0)
            hi_eff = np.clip(avg + hi, 0.0, 100.0)[None, :]
            lo_eff = np.clip(avg - lo, 0.0, 100.0)[None, :]
        pressure = self.slo_pressure_factor()
        if pressure > 1.0:
            # SLO-driven actuation: a burning shard tightens the high
            # thresholds, so nodes become actionable at lower utilization
            # while the error budget is being spent. Floored at the LOW
            # thresholds: a node must never classify high and low at
            # once (it would be an eviction source and a migration
            # destination simultaneously — thrash, not relief).
            hi_eff = np.maximum(hi_eff / pressure, lo_eff)
        raw_high = active & np.any(hi_on[None, :] & (util > hi_eff), axis=1)
        hi_eff_row = np.array(hi_eff[0])
        low = active & np.all(~lo_on[None, :] | (util < lo_eff), axis=1)
        # prod tier: a node can be overutilized on prod usage alone
        phi = self._vec(self.args.prod_high_thresholds)
        if (phi > 0).any():
            prod_util = (na.prod_usage + na.assigned_pending_prod) * 100.0 / alloc
            raw_high |= active & np.any(
                (phi > 0)[None, :] & (prod_util > phi[None, :]), axis=1
            )

        # debounce
        high = np.zeros_like(raw_high)
        for idx in np.nonzero(raw_high)[0]:
            count = self._over_counts.get(idx, 0) + (1 if update_debounce else 0)
            if update_debounce:
                self._over_counts[idx] = count
            if count >= self.args.anomaly_condition_count:
                high[idx] = True
        if update_debounce:
            for idx in list(self._over_counts):
                if not raw_high[idx]:
                    del self._over_counts[idx]
        cls = NodeClassification(
            low=low,
            high=high,
            raw_high=raw_high,
            utilization=util,
            hi_eff=np.where(hi_on, hi_eff_row, 0.0),
        )
        if update_debounce:
            self._last_cls = cls
        return cls

    def select_victims(
        self,
        bound_pods: Sequence[Pod],
        classification: Optional[NodeClassification] = None,
        shared_free: Optional[Dict[int, np.ndarray]] = None,
        exclude_uids: Optional[set] = None,
    ) -> List[Pod]:
        """Pick eviction candidates from debounced-high nodes.

        Order per node: lowest priority band first, then BE before LS,
        then largest estimated usage — and only pods that fit on at least
        one low node (utilization_util.go's sortPodsOnOneOverloadedNode).
        """
        # reuse this round's classification when the caller already ran
        # classify() — selecting victims must not advance the debounce
        # counters a second time. The cached classification is consumed
        # (one round = one classify), so a bare select_victims() next round
        # recomputes instead of acting on stale utilization.
        cls = classification or self._last_cls or self.classify()
        self._last_cls = None
        if not cls.high.any() or not cls.low.any():
            return []
        cfg = self.snapshot.config
        na = self.snapshot.nodes
        low_idx = np.nonzero(cls.low)[0]
        # a low node's headroom is shared across every pool that selects
        # it in one round (shared_free) — otherwise overlapping pools each
        # grant the same capacity twice and over-evict
        if shared_free is None:
            shared_free = {}
        low_free = np.stack(
            [
                shared_free.get(int(i), na.allocatable[i] - na.requested[i])
                for i in low_idx
            ]
        ) if low_idx.size else np.zeros((0, na.allocatable.shape[1]), np.float32)

        by_node: Dict[int, List[Pod]] = {}
        for pod in bound_pods:
            if pod.spec.node_name is None:
                continue
            if exclude_uids and pod.meta.uid in exclude_uids:
                continue
            # MaxInt32 eviction cost = never evict: selecting such a pod
            # would burn the per-node budget and low-node headroom on an
            # eviction the evictor chain will refuse (descheduling.go:33)
            if (
                ext.parse_eviction_cost(pod.meta.annotations)
                >= ext.EVICTION_COST_MAX
            ):
                continue
            idx = self.snapshot.node_id(pod.spec.node_name)
            if idx is not None and cls.high[idx]:
                by_node.setdefault(idx, []).append(pod)

        victims: List[Pod] = []
        # effective levels from the classification (deviation mode turns
        # configured offsets into absolute levels; raw offsets here would
        # weight the wrong dims and mis-project the eviction target)
        hi = (
            cls.hi_eff
            if cls.hi_eff is not None
            else self._vec(self.args.high_thresholds)
        )
        from ..ops.estimator import scale_vector

        relief = scale_vector(cfg.resources)
        # shared across all high nodes: a low node's free capacity is
        # consumed once, not once per overloaded source
        free = low_free.copy()
        for idx, pods in by_node.items():
            alloc = np.maximum(na.allocatable[idx], 1e-9)
            used = (
                np.maximum(na.usage_agg[idx], na.usage_avg[idx])
                + na.assigned_pending[idx]
            )
            target = alloc * np.where(
                hi > 0, (hi - self.args.target_margin_percent) / 100.0, np.inf
            )
            # weighted victim usage: only dims this node overuses count,
            # at their configured weights (sortPodsOnOneOverloadedNode;
            # the reference defaults every unlisted resource's weight to 1)
            w = np.array(
                [
                    float(dict(self.args.resource_weights).get(r, 1.0))
                    for r in cfg.resources
                ],
                np.float32,
            )
            overused = cls.utilization[idx] > np.where(hi > 0, hi, np.inf)

            w_eff = np.where(overused, w, 0.0)
            if not overused.any():
                w_eff = w  # prod-tier-only overuse: fall back to all dims

            def victim_usage(p: Pod) -> float:
                return float(cfg.res_vector(p.spec.requests) @ w_eff)

            # same priority band: lower eviction cost goes first
            # (descheduling.go:34-36)
            pods_sorted = sorted(
                pods,
                key=lambda p: (
                    int(p.priority_class),
                    -int(p.qos == ext.QoSClass.BE),
                    ext.parse_eviction_cost(p.meta.annotations),
                    -victim_usage(p),
                ),
            )
            evicted = 0
            for pod in pods_sorted:
                if evicted >= self.args.max_evictions_per_node:
                    break
                if np.all(used <= target + 1e-3):
                    break
                req = cfg.res_vector(pod.spec.requests)
                if self.args.node_fit:
                    fits = np.all(req[None, :] <= free + 1e-3, axis=1)
                    if not fits.any():
                        continue
                    tgt = int(np.argmax(fits))
                    free[tgt] -= req
                used = used - req * relief  # estimator-scaled relief per dim
                victims.append(pod)
                evicted += 1
        for k, i in enumerate(low_idx):
            shared_free[int(i)] = free[k]
        return victims


class LowNodeLoadBalance:
    """Framework adapter: runs LowNodeLoad as a Balance plugin
    (``low_node_load.go:137`` Balance entry point) — classify, select
    victims, push each through the profile's evictor chain. With
    ``pools`` configured, each pool runs the cycle over its selected
    nodes with its own thresholds/weights and debounce state
    (reference NodePools)."""

    name = "LowNodeLoad"

    def __init__(
        self,
        plugin: LowNodeLoad,
        pools: Sequence[NodePool] = (),
    ):
        self.plugin = plugin
        self.pools = list(pools)
        #: one LowNodeLoad per pool entry (debounce state must persist
        #: across rounds per pool; keyed by position so duplicate names
        #: cannot alias state)
        self._pool_plugins: List[LowNodeLoad] = [
            LowNodeLoad(plugin.snapshot, pool.args) for pool in self.pools
        ]

    def _pool_mask(self, pool: NodePool) -> np.ndarray:
        snap = self.plugin.snapshot
        n_bucket = snap.nodes.allocatable.shape[0]
        mask = np.zeros((n_bucket,), bool)
        for name, idx in snap._node_index.items():
            labels = snap.node_labels(name)
            if all(labels.get(k) == v for k, v in pool.node_selector.items()):
                mask[idx] = True
        return mask

    def balance(self, ctx) -> int:
        evicted = 0
        if self.pools:
            # overlapping pools share one view of low-node headroom and
            # never pick the same victim twice in a round
            shared_free: Dict[int, np.ndarray] = {}
            chosen: set = set()
            for k, pool in enumerate(self.pools):
                plugin = self._pool_plugins[k]
                cls = plugin.classify(node_mask=self._pool_mask(pool))
                victims = plugin.select_victims(
                    list(ctx.pods),
                    cls,
                    shared_free=shared_free,
                    exclude_uids=chosen,
                )
                for pod in victims:
                    chosen.add(pod.meta.uid)
                    if ctx.evict(pod, f"node overutilized (pool {pool.name})", self.name):
                        evicted += 1
            return evicted
        cls = self.plugin.classify()
        victims = self.plugin.select_victims(list(ctx.pods), cls)
        for pod in victims:
            if ctx.evict(pod, "node overutilized", self.name):
                evicted += 1
        return evicted
