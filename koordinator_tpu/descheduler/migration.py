"""PodMigrationJob controller + arbitrator.

Rebuild of ``pkg/descheduler/controllers/migration/`` (controller.go) and
its arbitrator (``arbitrator/arbitrator.go``, ``filter.go``, ``sort.go``):
migration jobs are sorted (lowest priority band / BE victims first),
filtered by per-namespace and global in-flight limits, then executed —
ReservationFirst mode creates a Reservation shaped like the victim's
replacement, waits until the scheduler binds it, and only then evicts
(``evictor/evictor_{native,delete,soft}.go`` → the ``evict_fn`` callback).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from ..api import extension as ext
from ..api.types import (
    MigrationMode,
    MigrationPhase,
    ObjectMeta,
    Pod,
    PodMigrationJob,
    Reservation,
    ReservationOwner,
    ReservationPhase,
)
from ..scheduler.plugins.reservation import ReservationManager

EvictFn = Callable[[Pod, str], bool]  # (victim, reason) -> evicted?


@dataclasses.dataclass
class ArbitratorArgs:
    """Reference ``arbitrator/filter.go`` limits."""

    max_migrating_global: int = 10
    max_migrating_per_namespace: int = 2


class Arbitrator:
    """Sort + filter candidate jobs (``arbitrator/arbitrator.go``)."""

    def __init__(self, args: Optional[ArbitratorArgs] = None):
        self.args = args or ArbitratorArgs()

    def arbitrate(
        self,
        jobs: Sequence[PodMigrationJob],
        pods_by_uid: Dict[str, Pod],
        in_flight: int,
        running_per_ns: Optional[Dict[str, int]] = None,
    ) -> List[PodMigrationJob]:
        def sort_key(job: PodMigrationJob):
            pod = pods_by_uid.get(job.pod_uid)
            if pod is None:
                return (99, 0)
            # lowest band first, BE before LS within a band
            return (
                int(pod.priority_class),
                0 if pod.qos == ext.QoSClass.BE else 1,
            )

        budget = max(self.args.max_migrating_global - in_flight, 0)
        # namespace caps count already-running migrations too
        per_ns: Dict[str, int] = dict(running_per_ns or {})
        selected: List[PodMigrationJob] = []
        for job in sorted(jobs, key=sort_key):
            if len(selected) >= budget:
                break
            pod = pods_by_uid.get(job.pod_uid)
            ns = pod.meta.namespace if pod else ""
            if per_ns.get(ns, 0) >= self.args.max_migrating_per_namespace:
                continue
            per_ns[ns] = per_ns.get(ns, 0) + 1
            selected.append(job)
        return selected


class MigrationController:
    """Drives PodMigrationJobs to completion."""

    def __init__(
        self,
        reservations: ReservationManager,
        evict_fn: EvictFn,
        arbitrator: Optional[Arbitrator] = None,
        job_timeout_s: float = 300.0,
    ):
        self.reservations = reservations
        self.evict_fn = evict_fn
        self.arbitrator = arbitrator or Arbitrator()
        self.job_timeout_s = job_timeout_s
        self.jobs: Dict[str, PodMigrationJob] = {}
        self._victims: Dict[str, Pod] = {}

    def submit(self, victim: Pod, mode: MigrationMode = MigrationMode.RESERVATION_FIRST) -> PodMigrationJob:
        name = f"migrate-{victim.meta.uid.replace('/', '-')}"
        if name in self.jobs and self.jobs[name].phase in (
            MigrationPhase.PENDING,
            MigrationPhase.RUNNING,
        ):
            return self.jobs[name]
        job = PodMigrationJob(
            meta=ObjectMeta(name=name), pod_uid=victim.meta.uid, mode=mode
        )
        self.jobs[name] = job
        self._victims[victim.meta.uid] = victim
        return job

    @property
    def in_flight(self) -> int:
        return sum(
            1 for j in self.jobs.values() if j.phase == MigrationPhase.RUNNING
        )

    def reconcile(self, now: Optional[float] = None) -> None:
        """One controller pass: arbitrate pending jobs, advance running ones.

        ReservationFirst (``controller.go`` reconcile): create a Reservation
        mirroring the victim (owners = the victim's labels, so the
        replacement matches), wait for it to become Available, then evict.
        Jobs stuck past ``job_timeout_s`` fail and release their
        reservation so the in-flight budget cannot leak away.
        """
        import time as _t

        now = now if now is not None else _t.time()
        running_per_ns: Dict[str, int] = {}
        for j in self.jobs.values():
            if j.phase == MigrationPhase.RUNNING:
                pod = self._victims.get(j.pod_uid)
                ns = pod.meta.namespace if pod else ""
                running_per_ns[ns] = running_per_ns.get(ns, 0) + 1

        pending = [
            j for j in self.jobs.values() if j.phase == MigrationPhase.PENDING
        ]
        for job in self.arbitrator.arbitrate(
            pending, self._victims, self.in_flight, running_per_ns
        ):
            victim = self._victims[job.pod_uid]
            # A victim with no labels yields an owner selector matching
            # every pod in the namespace — fall back to direct eviction
            # instead of creating a promiscuous reservation.
            if job.mode == MigrationMode.EVICT_DIRECTLY or not victim.meta.labels:
                ok = self.evict_fn(victim, "descheduled")
                job.phase = (
                    MigrationPhase.SUCCEEDED if ok else MigrationPhase.FAILED
                )
                continue
            r = Reservation(
                meta=ObjectMeta(name=f"{job.meta.name}-res"),
                requests=dict(victim.spec.requests),
                owners=[
                    ReservationOwner(
                        label_selector=dict(victim.meta.labels),
                        namespace=victim.meta.namespace,
                    )
                ],
                allocate_once=True,
            )
            self.reservations.add(r)
            job.reservation_name = r.meta.name
            job.phase = MigrationPhase.RUNNING

        self.reservations.schedule_pending()

        for job in self.jobs.values():
            if job.phase != MigrationPhase.RUNNING:
                continue
            r = self.reservations.get(job.reservation_name or "")
            victim = self._victims.get(job.pod_uid)
            if r is None or victim is None:
                job.phase = MigrationPhase.FAILED
                continue
            if now - job.create_time > self.job_timeout_s:
                self.reservations.expire_reservation(r.meta.name)
                job.phase = MigrationPhase.FAILED
                job.reason = "timed out waiting for replacement reservation"
                continue
            if r.phase == ReservationPhase.AVAILABLE:
                ok = self.evict_fn(victim, "descheduled; replacement reserved")
                job.phase = (
                    MigrationPhase.SUCCEEDED if ok else MigrationPhase.FAILED
                )
                if not ok:
                    self.reservations.expire_reservation(r.meta.name)
            elif r.phase == ReservationPhase.FAILED:
                job.phase = MigrationPhase.FAILED
