"""PodMigrationJob controller + arbitrator.

Rebuild of ``pkg/descheduler/controllers/migration/`` (controller.go) and
its arbitrator (``arbitrator/arbitrator.go``, ``filter.go``, ``sort.go``):
migration jobs are sorted (lowest priority band / BE victims first),
filtered by per-namespace and global in-flight limits, then executed —
ReservationFirst mode creates a Reservation shaped like the victim's
replacement, waits until the scheduler binds it, and only then evicts
(``evictor/evictor_{native,delete,soft}.go`` → the ``evict_fn`` callback).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from ..api import extension as ext
from ..api.types import (
    MigrationMode,
    MigrationPhase,
    ObjectMeta,
    Pod,
    PodMigrationJob,
    Reservation,
    ReservationOwner,
    ReservationPhase,
)
from ..scheduler.plugins.reservation import ReservationManager

EvictFn = Callable[[Pod, str], bool]  # (victim, reason) -> evicted?


def resolve_int_or_percent(value, replicas: int) -> int:
    """k8s intstr semantics (reference ``util.GetMaxMigrating`` /
    ``GetMaxUnavailable``): an int is absolute; "20%" scales against the
    workload's expected replicas (rounded up)."""
    import math

    if isinstance(value, str) and value.endswith("%"):
        return int(math.ceil(replicas * float(value[:-1]) / 100.0))
    return int(value)


@dataclasses.dataclass
class ArbitratorArgs:
    """Reference ``arbitrator/filter.go`` limits
    (``MigrationControllerArgs``)."""

    max_migrating_global: int = 10
    max_migrating_per_namespace: int = 2
    #: per-workload in-flight migration cap, int or "N%" of replicas
    #: (``filterMaxMigratingOrUnavailablePerWorkload``); None = unlimited
    max_migrating_per_workload: Optional[object] = None
    #: per-workload unavailable cap (migrating + already-unavailable pods)
    max_unavailable_per_workload: Optional[object] = None


class Arbitrator:
    """Sort + filter candidate jobs (``arbitrator/arbitrator.go``)."""

    def __init__(self, args: Optional[ArbitratorArgs] = None):
        self.args = args or ArbitratorArgs()

    def arbitrate(
        self,
        jobs: Sequence[PodMigrationJob],
        pods_by_uid: Dict[str, Pod],
        in_flight: int,
        running_per_ns: Optional[Dict[str, int]] = None,
        running_per_workload: Optional[Dict[str, int]] = None,
        replicas_by_owner: Optional[Dict[str, int]] = None,
        unavailable_by_owner: Optional[Dict[str, int]] = None,
    ) -> List[PodMigrationJob]:
        """``replicas_by_owner`` / ``unavailable_by_owner`` play the
        reference's controllerFinder role: expected replica count and
        currently-unavailable pod count per workload (owner uid). A pod
        without a controller (owner_uid "") skips workload limits, like
        the reference's nil-ownerRef early return."""

        def sort_key(job: PodMigrationJob):
            pod = pods_by_uid.get(job.pod_uid)
            if pod is None:
                return (99, 0)
            # lowest band first, BE before LS within a band
            return (
                int(pod.priority_class),
                0 if pod.qos == ext.QoSClass.BE else 1,
            )

        budget = max(self.args.max_migrating_global - in_flight, 0)
        # namespace/workload caps count already-running migrations too
        per_ns: Dict[str, int] = dict(running_per_ns or {})
        per_wl: Dict[str, int] = dict(running_per_workload or {})
        replicas = replicas_by_owner or {}
        unavailable = unavailable_by_owner or {}
        selected: List[PodMigrationJob] = []
        for job in sorted(jobs, key=sort_key):
            if len(selected) >= budget:
                break
            pod = pods_by_uid.get(job.pod_uid)
            ns = pod.meta.namespace if pod else ""
            if per_ns.get(ns, 0) >= self.args.max_migrating_per_namespace:
                continue
            owner = pod.meta.owner_uid if pod else ""
            if owner and not self._workload_allows(
                owner, per_wl, replicas, unavailable
            ):
                continue
            per_ns[ns] = per_ns.get(ns, 0) + 1
            if owner:
                per_wl[owner] = per_wl.get(owner, 0) + 1
            selected.append(job)
        return selected

    def _workload_allows(
        self,
        owner: str,
        per_wl: Dict[str, int],
        replicas: Dict[str, int],
        unavailable: Dict[str, int],
    ) -> bool:
        """filterMaxMigratingOrUnavailablePerWorkload: migrating-per-
        workload below the cap AND migrating+unavailable below the
        unavailable cap. Without replica info for the owner (no
        controller-finder wired) the limits are not evaluable — allow,
        like the reference's nil-ownerRef early return; a percent cap
        against unknown replicas would otherwise resolve to 0 and block
        every owned pod forever."""
        if owner not in replicas:
            return True
        n_replicas = replicas[owner]
        migrating = per_wl.get(owner, 0)
        if self.args.max_migrating_per_workload is not None:
            cap = resolve_int_or_percent(
                self.args.max_migrating_per_workload, n_replicas
            )
            if migrating >= cap:
                return False
        if self.args.max_unavailable_per_workload is not None:
            cap = resolve_int_or_percent(
                self.args.max_unavailable_per_workload, n_replicas
            )
            if migrating + unavailable.get(owner, 0) >= cap:
                return False
        return True


class MigrationController:
    """Drives PodMigrationJobs to completion."""

    def __init__(
        self,
        reservations: ReservationManager,
        evict_fn: EvictFn,
        arbitrator: Optional[Arbitrator] = None,
        job_timeout_s: float = 300.0,
        workload_info_fn: Optional[Callable[[str], tuple]] = None,
        freshness: Optional[Callable[[], bool]] = None,
        registry=None,
    ):
        self.reservations = reservations
        self.evict_fn = evict_fn
        self.arbitrator = arbitrator or Arbitrator()
        self.job_timeout_s = job_timeout_s
        #: controllerFinder analog: owner uid -> (expected_replicas,
        #: unavailable_pod_count) for the per-workload migration limits
        self.workload_info_fn = workload_info_fn
        #: gray-failure containment: zero-arg callable (the staleness
        #: watchdog's ``stale``) — eviction is evidence-hungry, so a
        #: whole reconcile pass refuses while informer snapshots are
        #: stale (jobs stay PENDING; nothing is lost, only delayed)
        self.freshness = freshness
        self.registry = registry
        #: reconcile passes refused on stale evidence (soak assertion)
        self.refused_stale = 0
        self.jobs: Dict[str, PodMigrationJob] = {}
        self._victims: Dict[str, Pod] = {}

    def submit(self, victim: Pod, mode: MigrationMode = MigrationMode.RESERVATION_FIRST) -> PodMigrationJob:
        name = f"migrate-{victim.meta.uid.replace('/', '-')}"
        if name in self.jobs and self.jobs[name].phase in (
            MigrationPhase.PENDING,
            MigrationPhase.RUNNING,
        ):
            return self.jobs[name]
        job = PodMigrationJob(
            meta=ObjectMeta(name=name), pod_uid=victim.meta.uid, mode=mode
        )
        self.jobs[name] = job
        self._victims[victim.meta.uid] = victim
        return job

    @property
    def in_flight(self) -> int:
        return sum(
            1 for j in self.jobs.values() if j.phase == MigrationPhase.RUNNING
        )

    def reconcile(self, now: Optional[float] = None) -> None:
        """One controller pass: arbitrate pending jobs, advance running ones.

        ReservationFirst (``controller.go`` reconcile): create a Reservation
        mirroring the victim (owners = the victim's labels, so the
        replacement matches), wait for it to become Available, then evict.
        Jobs stuck past ``job_timeout_s`` fail and release their
        reservation so the in-flight budget cannot leak away.
        """
        import time as _t

        # stale informer evidence: every eviction this pass would take is
        # justified by snapshots a silent-stalled watch may have frozen —
        # refuse the whole pass until events resume (pending jobs keep)
        if self.freshness is not None and self.freshness():
            self.refused_stale += 1
            if self.registry is not None:
                self.registry.get("stale_evidence_refusals_total").labels(
                    action="descheduler_eviction"
                ).inc()
            return

        now = now if now is not None else _t.time()
        running_per_ns: Dict[str, int] = {}
        running_per_wl: Dict[str, int] = {}
        for j in self.jobs.values():
            if j.phase == MigrationPhase.RUNNING:
                pod = self._victims.get(j.pod_uid)
                ns = pod.meta.namespace if pod else ""
                running_per_ns[ns] = running_per_ns.get(ns, 0) + 1
                if pod is not None and pod.meta.owner_uid:
                    wl = pod.meta.owner_uid
                    running_per_wl[wl] = running_per_wl.get(wl, 0) + 1

        pending = [
            j for j in self.jobs.values() if j.phase == MigrationPhase.PENDING
        ]
        replicas_by_owner: Dict[str, int] = {}
        unavailable_by_owner: Dict[str, int] = {}
        if self.workload_info_fn is not None:
            owners = {
                self._victims[j.pod_uid].meta.owner_uid
                for j in pending
                if j.pod_uid in self._victims
                and self._victims[j.pod_uid].meta.owner_uid
            }
            for owner in owners:
                replicas, unavail = self.workload_info_fn(owner)
                replicas_by_owner[owner] = replicas
                unavailable_by_owner[owner] = unavail
        for job in self.arbitrator.arbitrate(
            pending,
            self._victims,
            self.in_flight,
            running_per_ns,
            running_per_workload=running_per_wl,
            replicas_by_owner=replicas_by_owner,
            unavailable_by_owner=unavailable_by_owner,
        ):
            victim = self._victims[job.pod_uid]
            # A victim with no labels yields an owner selector matching
            # every pod in the namespace — fall back to direct eviction
            # instead of creating a promiscuous reservation.
            if job.mode == MigrationMode.EVICT_DIRECTLY or not victim.meta.labels:
                ok = self.evict_fn(victim, "descheduled")
                job.phase = (
                    MigrationPhase.SUCCEEDED if ok else MigrationPhase.FAILED
                )
                continue
            r = Reservation(
                meta=ObjectMeta(name=f"{job.meta.name}-res"),
                requests=dict(victim.spec.requests),
                owners=[
                    ReservationOwner(
                        label_selector=dict(victim.meta.labels),
                        namespace=victim.meta.namespace,
                    )
                ],
                allocate_once=True,
            )
            self.reservations.add(r)
            job.reservation_name = r.meta.name
            job.phase = MigrationPhase.RUNNING

        self.reservations.schedule_pending()

        for job in self.jobs.values():
            if job.phase != MigrationPhase.RUNNING:
                continue
            r = self.reservations.get(job.reservation_name or "")
            victim = self._victims.get(job.pod_uid)
            if r is None or victim is None:
                job.phase = MigrationPhase.FAILED
                continue
            if now - job.create_time > self.job_timeout_s:
                self.reservations.expire_reservation(r.meta.name)
                job.phase = MigrationPhase.FAILED
                job.reason = "timed out waiting for replacement reservation"
                continue
            if r.phase == ReservationPhase.AVAILABLE:
                ok = self.evict_fn(victim, "descheduled; replacement reserved")
                job.phase = (
                    MigrationPhase.SUCCEEDED if ok else MigrationPhase.FAILED
                )
                if not ok:
                    self.reservations.expire_reservation(r.meta.name)
            elif r.phase == ReservationPhase.FAILED:
                job.phase = MigrationPhase.FAILED
