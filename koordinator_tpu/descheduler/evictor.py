"""Evictors + evictability policy.

Rebuild of the reference's three eviction mechanisms
(``pkg/descheduler/evictions/`` + migration
``evictor/evictor_{native,delete,soft}.go``) and the vendored
DefaultEvictor evictability rules
(``framework/plugins/kubernetes/defaultevictor``): which pods may be
evicted at all, and how the eviction is delivered — eviction API
(PDB-respecting), plain delete, or a soft label the workload controller
reacts to.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Protocol

from ..api import extension as ext
from ..api.types import Pod, PodPhase

#: opt-out/opt-in annotation honored by the policy (sigs descheduler)
ANNOTATION_EVICT_OPT_OUT = "descheduler.alpha.kubernetes.io/prefer-no-eviction"
#: soft-eviction marker label; same key as the spec annotation so the
#: two can never diverge (reference descheduling.go:40-54)
LABEL_SOFT_EVICTION = ext.ANNOTATION_SOFT_EVICTION


@dataclasses.dataclass
class PodEvictionPolicy:
    """DefaultEvictor-style evictability predicate."""

    evict_system_critical: bool = False
    evict_local_storage: bool = False
    evict_ownerless: bool = False
    ignore_pvc_pods: bool = False
    #: pods at/above this priority are never evicted (system band default)
    priority_threshold: int = 10000
    #: extra label selector; empty matches all
    label_selector: Dict[str, str] = dataclasses.field(default_factory=dict)

    def evictable(self, pod: Pod) -> bool:
        if pod.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
            return False  # already terminal; nothing to evict
        if pod.meta.annotations.get(ANNOTATION_EVICT_OPT_OUT) == "true":
            return False
        # MaxInt32 eviction cost = never evict (descheduling.go:33)
        if ext.parse_eviction_cost(pod.meta.annotations) >= ext.EVICTION_COST_MAX:
            return False
        prio = pod.spec.priority or 0
        if not self.evict_system_critical and prio >= self.priority_threshold:
            return False
        if not self.evict_ownerless and pod.meta.labels.get("owner-kind") is None:
            # the reference inspects ownerReferences; the rebuild's Pod
            # carries the controller kind in a label set by the informer
            if "owner-kind" not in pod.meta.labels:
                return False
        if self.label_selector and not all(
            pod.meta.labels.get(k) == v for k, v in self.label_selector.items()
        ):
            return False
        return True


class Evictor(Protocol):
    name: str

    def evict(self, pod: Pod, reason: str) -> bool: ...


PDBCheck = Callable[[Pod], bool]  # True = disruption allowed


class NativeEvictor:
    """Eviction-API path (``evictor_native.go``): respects PDBs via the
    injected check; the apiserver call is the ``delete_fn`` callback."""

    name = "Eviction"

    def __init__(
        self,
        delete_fn: Callable[[Pod], bool],
        pdb_check: Optional[PDBCheck] = None,
    ):
        self.delete_fn = delete_fn
        self.pdb_check = pdb_check

    def evict(self, pod: Pod, reason: str) -> bool:
        if self.pdb_check is not None and not self.pdb_check(pod):
            return False
        return self.delete_fn(pod)


class DeleteEvictor:
    """Plain pod delete (``evictor_delete.go``): no PDB protection."""

    name = "Delete"

    def __init__(self, delete_fn: Callable[[Pod], bool]):
        self.delete_fn = delete_fn

    def evict(self, pod: Pod, reason: str) -> bool:
        return self.delete_fn(pod)


class SoftEvictor:
    """Label-only eviction (``evictor_soft.go``): annotate the pod and
    let its workload controller do a graceful replace."""

    name = "SoftEviction"

    def __init__(self) -> None:
        self.marked: List[Pod] = []

    def evict(self, pod: Pod, reason: str) -> bool:
        if pod.meta.labels.get(LABEL_SOFT_EVICTION) == "true":
            return False  # already marked
        pod.meta.labels[LABEL_SOFT_EVICTION] = "true"
        # SoftEvictionSpec under the reference's annotation name
        # (descheduling.go:40-54 GetSoftEvictionSpec)
        import json

        pod.meta.annotations[ext.ANNOTATION_SOFT_EVICTION] = json.dumps(
            {
                "timestamp": int(time.time()),
                "reason": reason,
                "initiator": "koord-descheduler",
            }
        )
        self.marked.append(pod)
        return True
