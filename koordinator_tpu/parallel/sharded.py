"""Multi-chip solver sharding over a (dp, tp) device mesh.

The scale dimension the reference struggles with is nodes × pending pods
(SURVEY §5 "long-context" analog — its only mitigations are
``percentageOfNodesToScore`` and 16-way goroutine chunking). Here the
(P, N) work is sharded over ICI: the pending-pod batch axis is "dp", the
node-table axis is "tp". XLA's SPMD partitioner inserts the collectives
(the top-k/argmin over the sharded node axis becomes an all-reduce-style
combine riding ICI; DCN would only enter for multi-slice meshes).

``sharded_assign`` is the GSPMD path: the *same* jitted program as the
single-chip solver, with sharding constraints on inputs. A hand-scheduled
``shard_map`` variant can replace it where the partitioner's choices are
suboptimal; semantics are identical either way.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import devprof as _devprof
from ..obs.devprof import NULL_WATCH as _NULL_WATCH
from ..ops.solver import (
    NodeState,
    PodBatch,
    QuotaState,
    SolverParams,
    SolveResult,
    assign,
)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """Factor devices into a (dp, tp) mesh, tp (node axis) ≥ dp.

    Falls back to the host CPU backend when the default backend has fewer
    than ``n_devices`` chips (the virtual-device dry-run path: environments
    pin ``jax_platforms="axon,cpu"`` so the cpu backend co-exists and honors
    ``--xla_force_host_platform_device_count``).
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            pass
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    n = len(devs)
    dp = 1
    while n % (dp * 2) == 0 and (dp * 2) * (dp * 2) <= n:
        dp *= 2
    tp = n // dp
    return Mesh(np.asarray(devs).reshape(dp, tp), ("dp", "tp"))


def put_resident(mesh: Mesh, tree):
    """Place a node-axis RESIDENT table (NodeState / NumaState /
    DeviceState pytree of ``[N, ...]`` arrays) onto the mesh: axis 0
    sharded on ``tp``, trailing axes replicated. This is the mesh-mode
    lowering the ``BatchScheduler`` runs ONCE per full re-lower — the
    steady state then refreshes these shards in place via
    ``ops.solver.scatter_rows_sharded`` (donated, no resharding copy)
    instead of re-placing per cycle."""
    if tree is None:
        return None
    sh = NamedSharding(mesh, P("tp"))
    return jax.device_put(tree, jax.tree.map(lambda _a: sh, tree))


def _pod_spec() -> PodBatch:
    return PodBatch(
        requests=P("dp", None),
        estimate=P("dp", None),
        priority=P("dp"),
        is_prod=P("dp"),
        valid=P("dp"),
        gang_id=P("dp"),
        # gang_min/quota arrays are indexed by gang/quota id (batch-global),
        # not pod row: replicate so segment ops stay local.
        gang_min=P(),
        quota_chain=P("dp", None),
        qos=P("dp"),
        gpu_whole=P("dp"),
        gpu_share=P("dp"),
        rdma=P("dp"),
        fpga=P("dp"),
        gang_nonstrict=P(),
        numa_required=P("dp"),
    )


def shard_solver_inputs(
    mesh: Mesh,
    pods: PodBatch | None = None,
    nodes: NodeState | None = None,
    quotas=None,
    numa=None,
    devices=None,
    node_mask=None,
    dev_carry=None,
    params=None,
):
    """Place a production solve's inputs onto the mesh (pod rows on dp,
    node-axis tables on tp, everything id-indexed replicated) and return
    them in the same order. ``assign`` is jitted WITHOUT explicit
    shardings, so GSPMD picks the layout up from these placements — the
    BatchScheduler's mesh mode is exactly this call before dispatch
    (reference analog: the parallelism wired into the real scheduler at
    ``cmd/koord-scheduler/app/server.go:417``)."""

    def put(tree, spec_fn):
        if tree is None:
            return None
        return jax.device_put(
            tree, jax.tree.map(lambda a: NamedSharding(mesh, spec_fn(a)), tree)
        )

    rep = lambda _a: P()
    tp0 = lambda _a: P("tp")       # axis 0 on tp, rest replicated
    out = (
        put(pods, lambda a: _pod_leaf_spec(pods, a)),
        put(nodes, tp0),
        put(quotas, rep),
        put(numa, tp0),
        put(devices, tp0),
        put(node_mask, lambda _a: P("dp", "tp")),
        put(dev_carry, tp0),
        put(params, rep),
    )
    return out


def _pod_leaf_spec(pods: PodBatch, leaf) -> P:
    """Per-leaf pod sharding: pod-row arrays on dp; gang/quota-id-indexed
    arrays replicated (segment ops must stay local)."""
    for name in ("gang_min", "gang_nonstrict"):
        if getattr(pods, name) is leaf:
            return P()
    return P("dp") if leaf.ndim == 1 else P("dp", *([None] * (leaf.ndim - 1)))


def _node_spec() -> NodeState:
    return NodeState(
        allocatable=P("tp", None),
        requested=P("tp", None),
        estimated_used=P("tp", None),
        prod_used=P("tp", None),
        metric_fresh=P("tp"),
        schedulable=P("tp"),
        cpu_amp=P("tp"),
        custom_thresholds=P("tp", None),
        custom_prod_thresholds=P("tp", None),
    )


def sharded_assign(
    mesh: Mesh,
    pods: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    max_rounds: int = 24,
    devprof=None,
) -> SolveResult:
    """Run the round solver SPMD over the mesh.

    Pod arrays are sharded on dp, the node table on tp, params replicated.
    Output assignment is sharded on dp; node usage tensors on tp.

    ``devprof`` (a :class:`~..obs.devprof.DevProf`) wraps the dispatch in
    a signature-carrying watch window so mesh-path retraces land in the
    CompileLedger with an attributable cause (PR 8 standing rule).
    """
    pod_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), _pod_spec())
    node_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), _node_spec())
    rep = NamedSharding(mesh, P())
    param_sh = jax.tree.map(lambda _: rep, params)
    out_sh = SolveResult(
        assignment=NamedSharding(mesh, P("dp")),
        node_requested=NamedSharding(mesh, P("tp", None)),
        node_estimated_used=NamedSharding(mesh, P("tp", None)),
        node_prod_used=NamedSharding(mesh, P("tp", None)),
        quota_used=rep,
        rounds_used=rep,
        node_dev_slots=NamedSharding(mesh, P("tp", None)),
        node_rdma_free=NamedSharding(mesh, P("tp")),
        node_fpga_free=NamedSharding(mesh, P("tp")),
        node_zone_free=NamedSharding(mesh, P("tp", None, None)),
        pod_zone=NamedSharding(mesh, P("dp")),
        pod_zone_charge=NamedSharding(mesh, P("dp", None)),
    )

    def _traced_assign(p, n, pr):
        # retrace ledger hook (obs.devprof): runs at trace time only
        _devprof.tracing("sharded_assign")
        return assign(p, n, pr, max_rounds=max_rounds)

    fn = jax.jit(
        _traced_assign,
        in_shardings=(pod_sh, node_sh, param_sh),
        out_shardings=out_sh,
    )
    pods = jax.device_put(pods, pod_sh)
    nodes = jax.device_put(nodes, node_sh)
    params = jax.device_put(params, param_sh)
    with (
        devprof.watch(
            "sharded_assign",
            dp=mesh.shape["dp"],
            tp=mesh.shape["tp"],
            bucket=pods.requests.shape[0],
            n=nodes.allocatable.shape[0],
            max_rounds=max_rounds,
        )
        if devprof is not None
        else _NULL_WATCH
    ) as w:
        out = fn(pods, nodes, params)
        w.result(out)
    return out


def sharded_solve_stream(
    mesh: Mesh,
    pods_stacked: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    max_rounds: int = 24,
    approx_topk: bool = False,
    devprof=None,
):
    """Pipelined multi-batch solve, SPMD over the mesh: batch axis
    unsharded (scan), pod rows on dp, node table on tp. This is the
    multi-chip serving configuration — one dispatch per stream, capacity
    threaded on device, collectives riding ICI.

    Returns ``(assignments [B, P], final NodeState, placed [B], quotas)``.
    ``devprof`` wraps the dispatch in a watch window (see
    :func:`sharded_assign`).
    """
    from ..ops.solver import solve_stream

    pod_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, P(None, *s)), _pod_spec()
    )
    node_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), _node_spec())
    rep = NamedSharding(mesh, P())
    param_sh = jax.tree.map(lambda _: rep, params)

    def _traced_stream(p, n, pr):
        _devprof.tracing("sharded_solve_stream")
        return solve_stream(
            p, n, pr, max_rounds=max_rounds, approx_topk=approx_topk
        )

    fn = jax.jit(
        _traced_stream,
        in_shardings=(pod_sh, node_sh, param_sh),
        out_shardings=(
            NamedSharding(mesh, P(None, "dp")),
            jax.tree.map(lambda s: NamedSharding(mesh, s), _node_spec()),
            rep,
            jax.tree.map(lambda _: rep, QuotaState.disabled(1)),
        ),
    )
    pods_stacked = jax.device_put(pods_stacked, pod_sh)
    nodes = jax.device_put(nodes, node_sh)
    params = jax.device_put(params, param_sh)
    with (
        devprof.watch(
            "sharded_solve_stream",
            dp=mesh.shape["dp"],
            tp=mesh.shape["tp"],
            batches=pods_stacked.requests.shape[0],
            bucket=pods_stacked.requests.shape[1],
            n=nodes.allocatable.shape[0],
            max_rounds=max_rounds,
            approx_topk=approx_topk,
        )
        if devprof is not None
        else _NULL_WATCH
    ) as w:
        out = fn(pods_stacked, nodes, params)
        w.result(out)
    return out


def _pad_nodes(nodes: NodeState, pad: int) -> NodeState:
    """Append ``pad`` infeasible node rows (zero capacity, unschedulable)
    so the table divides evenly across the tp axis."""
    import jax.numpy as jnp

    def zrows(a):
        return jnp.concatenate(
            [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]
        )

    return NodeState(
        allocatable=zrows(nodes.allocatable),
        requested=zrows(nodes.requested),
        estimated_used=zrows(nodes.estimated_used),
        prod_used=zrows(nodes.prod_used),
        metric_fresh=zrows(nodes.metric_fresh),
        schedulable=zrows(nodes.schedulable),
        cpu_amp=jnp.concatenate(
            [nodes.cpu_amp, jnp.ones(pad, nodes.cpu_amp.dtype)]
        ),
        custom_thresholds=zrows(nodes.custom_thresholds),
        custom_prod_thresholds=zrows(nodes.custom_prod_thresholds),
    )


def shard_map_nominate(
    mesh: Mesh,
    pods: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    topk: int = 4,
    nomination_jitter: float = 4.0,
    devprof=None,
):
    """Hand-scheduled nomination for node tables too large for one chip's
    HBM: each device holds a 1/tp shard of the node table, computes the
    cost block + local top-k over its shard, and the [P, tp·K] candidate
    sets are combined with one all-gather over ICI (the cross-device
    reduction is K values per pod, not the [P, N] cost matrix — the same
    communication shape as ring-attention's per-block softmax stats).

    Pod arrays are replicated across tp (they're [P, D] — tiny); the
    returned global candidates ([P, K] values + global node indices) feed
    the host/replicated commit phase. Use when GSPMD's choice for the
    fused cost+topk is suboptimal; semantics match the single-chip
    nomination exactly (modulo the documented jitter hash, which uses
    *global* node indices and is therefore shard-invariant).
    """
    from functools import partial

    import jax.numpy as jnp

    try:
        from jax import shard_map as _smap

        # the replication checker can't see through all_gather+top_k;
        # outputs ARE replicated (identical candidate sets on every shard)
        shard_map = partial(_smap, check_vma=False)
    except (ImportError, TypeError):  # older jax
        from jax.experimental.shard_map import shard_map as _smap_old

        shard_map = partial(_smap_old, check_rep=False)

    from ..ops import costs as cost_ops
    from ..ops import masks as mask_ops

    n = nodes.allocatable.shape[0]
    tp = mesh.shape["tp"]
    pad = (-n) % tp
    if pad:
        # pad the node table to a multiple of tp with infeasible rows
        # (schedulable=False → cost inf): a padded row can only surface
        # as a candidate when every real node is infeasible for that pod,
        # and then its -inf value marks it invalid to the commit phase
        nodes = _pad_nodes(nodes, pad)
        n += pad
    shard_w = n // tp
    p = pods.requests.shape[0]

    node_specs = NodeState(
        allocatable=P("tp", None),
        requested=P("tp", None),
        estimated_used=P("tp", None),
        prod_used=P("tp", None),
        metric_fresh=P("tp"),
        schedulable=P("tp"),
        cpu_amp=P("tp"),
        custom_thresholds=P("tp", None),
        custom_prod_thresholds=P("tp", None),
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(), pods),      # replicated pods
            node_specs,
            jax.tree.map(lambda _: P(), params),
        ),
        out_specs=(P(), P()),
    )
    def nominate(pods_l, nodes_l, params_l):
        _devprof.tracing("shard_map_nominate")
        # global node index of this shard's rows — the jitter hash and the
        # returned candidate indices must be shard-position-aware
        tpi = jax.lax.axis_index("tp")
        g0 = tpi * shard_w
        free = nodes_l.allocatable - nodes_l.requested
        feas = mask_ops.fit_mask(pods_l.requests, free)
        feas &= mask_ops.usage_threshold_mask(
            pods_l.estimate,
            nodes_l.estimated_used,
            nodes_l.allocatable,
            params_l.usage_thresholds,
            nodes_l.metric_fresh,
            node_custom=nodes_l.custom_thresholds,
        )
        feas &= nodes_l.schedulable[None, :]
        cost = cost_ops.load_aware_cost(
            pods_l.estimate,
            nodes_l.estimated_used,
            nodes_l.allocatable,
            params_l.score_weights,
            metric_fresh=nodes_l.metric_fresh,
        )
        if nomination_jitter > 0.0:
            pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
            ni = (g0.astype(jnp.uint32) + jnp.arange(shard_w, dtype=jnp.uint32))[
                None, :
            ]
            h = (pi * jnp.uint32(2654435761) + ni * jnp.uint32(40503)) & jnp.uint32(
                0xFFFF
            )
            cost = cost + h.astype(jnp.float32) * (nomination_jitter / 65536.0)
        cost = jnp.where(feas, cost, jnp.inf)
        k = min(topk, shard_w)
        neg_local, idx_local = jax.lax.top_k(-cost, k)       # [P, K] per shard
        gidx_local = (idx_local + g0).astype(jnp.int32)
        # one all-gather of K candidates per pod per shard — O(P·K·tp),
        # never O(P·N)
        neg_all = jax.lax.all_gather(neg_local, "tp", axis=1, tiled=True)
        gidx_all = jax.lax.all_gather(gidx_local, "tp", axis=1, tiled=True)
        sel_neg, sel_pos = jax.lax.top_k(neg_all, k)          # [P, K] global
        sel_idx = jnp.take_along_axis(gidx_all, sel_pos, axis=1)
        return sel_neg, sel_idx

    with (
        devprof.watch(
            "shard_map_nominate",
            tp=tp,
            bucket=p,
            n=n,
            topk=topk,
            nomination_jitter=nomination_jitter,
        )
        if devprof is not None
        else _NULL_WATCH
    ) as w:
        out = nominate(
            jax.device_put(
                pods,
                jax.tree.map(lambda _: NamedSharding(mesh, P()), pods),
            ),
            jax.device_put(
                nodes,
                jax.tree.map(
                    lambda s: NamedSharding(mesh, s), node_specs
                ),
            ),
            params,
        )
        w.result(out)
    return out
