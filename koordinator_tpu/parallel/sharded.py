"""Multi-chip solver sharding over a (dp, tp) device mesh.

The scale dimension the reference struggles with is nodes × pending pods
(SURVEY §5 "long-context" analog — its only mitigations are
``percentageOfNodesToScore`` and 16-way goroutine chunking). Here the
(P, N) work is sharded over ICI: the pending-pod batch axis is "dp", the
node-table axis is "tp". XLA's SPMD partitioner inserts the collectives
(the top-k/argmin over the sharded node axis becomes an all-reduce-style
combine riding ICI; DCN would only enter for multi-slice meshes).

``sharded_assign`` is the GSPMD path: the *same* jitted program as the
single-chip solver, with sharding constraints on inputs. A hand-scheduled
``shard_map`` variant can replace it where the partitioner's choices are
suboptimal; semantics are identical either way.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.solver import NodeState, PodBatch, SolverParams, SolveResult, assign


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """Factor devices into a (dp, tp) mesh, tp (node axis) ≥ dp.

    Falls back to the host CPU backend when the default backend has fewer
    than ``n_devices`` chips (the virtual-device dry-run path: environments
    pin ``jax_platforms="axon,cpu"`` so the cpu backend co-exists and honors
    ``--xla_force_host_platform_device_count``).
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            pass
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    n = len(devs)
    dp = 1
    while n % (dp * 2) == 0 and (dp * 2) * (dp * 2) <= n:
        dp *= 2
    tp = n // dp
    return Mesh(np.asarray(devs).reshape(dp, tp), ("dp", "tp"))


def _pod_spec() -> PodBatch:
    return PodBatch(
        requests=P("dp", None),
        estimate=P("dp", None),
        priority=P("dp"),
        is_prod=P("dp"),
        valid=P("dp"),
        gang_id=P("dp"),
        # gang_min/quota arrays are indexed by gang/quota id (batch-global),
        # not pod row: replicate so segment ops stay local.
        gang_min=P(),
        quota_chain=P("dp", None),
        qos=P("dp"),
        gpu_whole=P("dp"),
        gpu_share=P("dp"),
    )


def _node_spec() -> NodeState:
    return NodeState(
        allocatable=P("tp", None),
        requested=P("tp", None),
        estimated_used=P("tp", None),
        prod_used=P("tp", None),
        metric_fresh=P("tp"),
        schedulable=P("tp"),
    )


def sharded_assign(
    mesh: Mesh,
    pods: PodBatch,
    nodes: NodeState,
    params: SolverParams,
    max_rounds: int = 24,
) -> SolveResult:
    """Run the round solver SPMD over the mesh.

    Pod arrays are sharded on dp, the node table on tp, params replicated.
    Output assignment is sharded on dp; node usage tensors on tp.
    """
    pod_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), _pod_spec())
    node_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), _node_spec())
    rep = NamedSharding(mesh, P())
    param_sh = jax.tree.map(lambda _: rep, params)
    out_sh = SolveResult(
        assignment=NamedSharding(mesh, P("dp")),
        node_requested=NamedSharding(mesh, P("tp", None)),
        node_estimated_used=NamedSharding(mesh, P("tp", None)),
        quota_used=rep,
        rounds_used=rep,
    )

    fn = jax.jit(
        functools.partial(assign, max_rounds=max_rounds),
        in_shardings=(pod_sh, node_sh, param_sh),
        out_shardings=out_sh,
    )
    pods = jax.device_put(pods, pod_sh)
    nodes = jax.device_put(nodes, node_sh)
    params = jax.device_put(params, param_sh)
    return fn(pods, nodes, params)
