"""gRPC transport for the RuntimeHookService channel.

The reference's proxy talks to hook servers over gRPC on a unix socket
(``pkg/runtimeproxy/dispatcher`` → registered ``RuntimeHookServer``
addresses; koordlet's ``runtimehooks/proxyserver`` is the other end).
This module is that wire path: :func:`serve_hooks` exposes a hook handler
(e.g. :class:`..runtimeproxy.hookserver.KoordletHookServer`'s ``handle``)
as a gRPC service, and :class:`RemoteHookHandler` is the proxy-side
callable that plugs into a ``HookServerRegistration`` — the dispatcher
cannot tell a remote server from an in-process one.

Like the snapshot channel, the service is registered through
``grpc.method_handlers_generic_handler`` (the image ships protoc without
the grpc python plugin); the wire contract is
``runtime/proto/runtimehook.proto``.
"""

from __future__ import annotations

import dataclasses
from concurrent import futures
from typing import Callable, Optional

import grpc

from ..runtime.proto import runtimehook_pb2 as pb
from .proto import (
    ContainerMetadata,
    ContainerResourceHookRequest,
    ContainerResourceHookResponse,
    LinuxContainerResources,
    PodSandboxHookRequest,
    PodSandboxHookResponse,
    PodSandboxMetadata,
    RuntimeHookType,
)

SERVICE_NAME = "koordinator_tpu.runtimeproxy.RuntimeHookService"

#: hook -> (rpc name, request kind); sandbox hooks ride
#: PodSandboxHookRequest, container hooks ContainerResourceHookRequest
_SANDBOX_HOOKS = (
    RuntimeHookType.PRE_RUN_POD_SANDBOX,
    RuntimeHookType.POST_STOP_POD_SANDBOX,
)


def _is_sandbox(hook: RuntimeHookType) -> bool:
    return hook in _SANDBOX_HOOKS


# ---- dataclass <-> pb codecs ----


def _res_to_pb(res: Optional[LinuxContainerResources]) -> pb.LinuxContainerResources:
    out = pb.LinuxContainerResources()
    if res is None:
        return out
    for f in dataclasses.fields(res):
        val = getattr(res, f.name)
        if f.name == "unified":
            out.unified.update(val)
        elif val:
            setattr(out, f.name, val)
    return out


def _res_from_pb(msg: pb.LinuxContainerResources) -> Optional[LinuxContainerResources]:
    res = LinuxContainerResources(
        cpu_period=msg.cpu_period,
        cpu_quota=msg.cpu_quota,
        cpu_shares=msg.cpu_shares,
        memory_limit_in_bytes=msg.memory_limit_in_bytes,
        oom_score_adj=msg.oom_score_adj,
        cpuset_cpus=msg.cpuset_cpus,
        cpuset_mems=msg.cpuset_mems,
        unified=dict(msg.unified),
    )
    if not any(dataclasses.asdict(res).values()):
        return None
    return res


def sandbox_req_to_pb(req: PodSandboxHookRequest) -> pb.PodSandboxHookRequest:
    msg = pb.PodSandboxHookRequest(
        runtime_handler=req.runtime_handler,
        cgroup_parent=req.cgroup_parent,
    )
    msg.pod_meta.name = req.pod_meta.name
    msg.pod_meta.uid = req.pod_meta.uid
    msg.pod_meta.namespace = req.pod_meta.namespace
    msg.pod_meta.attempt = req.pod_meta.attempt
    msg.labels.update(req.labels)
    msg.annotations.update(req.annotations)
    msg.overhead.CopyFrom(_res_to_pb(req.overhead))
    msg.resources.CopyFrom(_res_to_pb(req.resources))
    return msg


def sandbox_req_from_pb(msg: pb.PodSandboxHookRequest) -> PodSandboxHookRequest:
    return PodSandboxHookRequest(
        pod_meta=PodSandboxMetadata(
            name=msg.pod_meta.name,
            uid=msg.pod_meta.uid,
            namespace=msg.pod_meta.namespace or "default",
            attempt=msg.pod_meta.attempt,
        ),
        runtime_handler=msg.runtime_handler,
        labels=dict(msg.labels),
        annotations=dict(msg.annotations),
        cgroup_parent=msg.cgroup_parent,
        overhead=_res_from_pb(msg.overhead),
        resources=_res_from_pb(msg.resources),
    )


def sandbox_resp_to_pb(
    resp: Optional[PodSandboxHookResponse],
) -> pb.PodSandboxHookResponse:
    msg = pb.PodSandboxHookResponse()
    if resp is None:
        return msg
    msg.labels.update(resp.labels)
    msg.annotations.update(resp.annotations)
    msg.cgroup_parent = resp.cgroup_parent
    msg.resources.CopyFrom(_res_to_pb(resp.resources))
    return msg


def sandbox_resp_from_pb(msg: pb.PodSandboxHookResponse) -> PodSandboxHookResponse:
    return PodSandboxHookResponse(
        labels=dict(msg.labels),
        annotations=dict(msg.annotations),
        cgroup_parent=msg.cgroup_parent,
        resources=_res_from_pb(msg.resources),
    )


def container_req_to_pb(
    req: ContainerResourceHookRequest,
) -> pb.ContainerResourceHookRequest:
    msg = pb.ContainerResourceHookRequest(
        pod_cgroup_parent=req.pod_cgroup_parent,
    )
    msg.pod_meta.name = req.pod_meta.name
    msg.pod_meta.uid = req.pod_meta.uid
    msg.pod_meta.namespace = req.pod_meta.namespace
    msg.container_meta.name = req.container_meta.name
    msg.container_meta.id = req.container_meta.id
    msg.container_meta.attempt = req.container_meta.attempt
    msg.container_annotations.update(req.container_annotations)
    msg.pod_labels.update(req.pod_labels)
    msg.pod_annotations.update(req.pod_annotations)
    msg.container_envs.update(req.container_envs)
    msg.container_resources.CopyFrom(_res_to_pb(req.container_resources))
    return msg


def container_req_from_pb(
    msg: pb.ContainerResourceHookRequest,
) -> ContainerResourceHookRequest:
    return ContainerResourceHookRequest(
        pod_meta=PodSandboxMetadata(
            name=msg.pod_meta.name,
            uid=msg.pod_meta.uid,
            namespace=msg.pod_meta.namespace or "default",
        ),
        container_meta=ContainerMetadata(
            name=msg.container_meta.name,
            id=msg.container_meta.id,
            attempt=msg.container_meta.attempt,
        ),
        container_annotations=dict(msg.container_annotations),
        container_resources=_res_from_pb(msg.container_resources),
        pod_labels=dict(msg.pod_labels),
        pod_annotations=dict(msg.pod_annotations),
        pod_cgroup_parent=msg.pod_cgroup_parent,
        container_envs=dict(msg.container_envs),
    )


def container_resp_to_pb(
    resp: Optional[ContainerResourceHookResponse],
) -> pb.ContainerResourceHookResponse:
    msg = pb.ContainerResourceHookResponse()
    if resp is None:
        return msg
    msg.container_annotations.update(resp.container_annotations)
    msg.pod_cgroup_parent = resp.pod_cgroup_parent
    msg.container_envs.update(resp.container_envs)
    msg.container_resources.CopyFrom(_res_to_pb(resp.container_resources))
    return msg


def container_resp_from_pb(
    msg: pb.ContainerResourceHookResponse,
) -> ContainerResourceHookResponse:
    return ContainerResourceHookResponse(
        container_annotations=dict(msg.container_annotations),
        container_resources=_res_from_pb(msg.container_resources),
        pod_cgroup_parent=msg.pod_cgroup_parent,
        container_envs=dict(msg.container_envs),
    )


# ---- server side (koordlet hook server behind gRPC) ----


def serve_hooks(
    handler: Callable[[RuntimeHookType, object], object],
    address: str = "127.0.0.1:0",
    max_workers: int = 4,
) -> tuple[grpc.Server, int]:
    """Expose ``handler(hook_type, dataclass_request) -> dataclass|None``
    as the RuntimeHookService; returns (server, bound_port)."""

    def method(hook: RuntimeHookType):
        if _is_sandbox(hook):
            def call(req_pb, _ctx):
                resp = handler(hook, sandbox_req_from_pb(req_pb))
                return sandbox_resp_to_pb(resp)

            return grpc.unary_unary_rpc_method_handler(
                call,
                request_deserializer=pb.PodSandboxHookRequest.FromString,
                response_serializer=pb.PodSandboxHookResponse.SerializeToString,
            )

        def call(req_pb, _ctx):
            resp = handler(hook, container_req_from_pb(req_pb))
            return container_resp_to_pb(resp)

        return grpc.unary_unary_rpc_method_handler(
            call,
            request_deserializer=pb.ContainerResourceHookRequest.FromString,
            response_serializer=pb.ContainerResourceHookResponse.SerializeToString,
        )

    handlers = {hook.value: method(hook) for hook in RuntimeHookType}
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    port = server.add_insecure_port(address)
    server.start()
    return server, port


# ---- proxy side (remote hook handler for the dispatcher) ----


class RemoteHookHandler:
    """Proxy-side callable for a remote hook server: drop-in for the
    ``handler`` of a ``HookServerRegistration`` — serializes the request,
    calls the RPC, returns the dataclass response. gRPC errors propagate
    so the dispatcher's failure policy decides (Fail aborts the CRI call,
    Ignore proceeds)."""

    def __init__(self, target: str):
        self._channel = grpc.insecure_channel(target)
        self._stubs = {}
        for hook in RuntimeHookType:
            if _is_sandbox(hook):
                self._stubs[hook] = self._channel.unary_unary(
                    f"/{SERVICE_NAME}/{hook.value}",
                    request_serializer=pb.PodSandboxHookRequest.SerializeToString,
                    response_deserializer=pb.PodSandboxHookResponse.FromString,
                )
            else:
                self._stubs[hook] = self._channel.unary_unary(
                    f"/{SERVICE_NAME}/{hook.value}",
                    request_serializer=pb.ContainerResourceHookRequest.SerializeToString,
                    response_deserializer=pb.ContainerResourceHookResponse.FromString,
                )

    def __call__(self, hook: RuntimeHookType, request):
        if _is_sandbox(hook):
            return sandbox_resp_from_pb(self._stubs[hook](sandbox_req_to_pb(request)))
        return container_resp_from_pb(self._stubs[hook](container_req_to_pb(request)))

    def close(self) -> None:
        self._channel.close()
