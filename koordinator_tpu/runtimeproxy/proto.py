"""RuntimeHookService wire types.

Rebuild of ``apis/runtime/v1alpha1/api.proto``: the contract between the
CRI interposer (:mod:`server`) and hook servers. The reference ships this
as gRPC/proto3; the rebuild keeps the exact message shapes as dataclasses
so the dispatcher, store, and merge semantics stay protocol-faithful while
transport stays in-process (a real deployment would put these back on a
unix-socket gRPC channel — the shapes are 1:1 with the proto).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional


class RuntimeHookType(enum.Enum):
    """The seven RPCs of RuntimeHookService (api.proto:147-170)."""

    PRE_RUN_POD_SANDBOX = "PreRunPodSandbox"
    POST_STOP_POD_SANDBOX = "PostStopPodSandbox"
    PRE_CREATE_CONTAINER = "PreCreateContainer"
    PRE_START_CONTAINER = "PreStartContainer"
    POST_START_CONTAINER = "PostStartContainer"
    POST_STOP_CONTAINER = "PostStopContainer"
    PRE_UPDATE_CONTAINER_RESOURCES = "PreUpdateContainerResources"


#: hook types whose response is merged into the forwarded CRI request;
#: post-hooks are observational (reference server/cri/runtime.go)
PRE_HOOKS = frozenset(
    {
        RuntimeHookType.PRE_RUN_POD_SANDBOX,
        RuntimeHookType.PRE_CREATE_CONTAINER,
        RuntimeHookType.PRE_START_CONTAINER,
        RuntimeHookType.PRE_UPDATE_CONTAINER_RESOURCES,
    }
)


@dataclasses.dataclass
class LinuxContainerResources:
    """api.proto LinuxContainerResources (the CRI subset the hooks touch)."""

    cpu_period: int = 0
    cpu_quota: int = 0
    cpu_shares: int = 0
    memory_limit_in_bytes: int = 0
    oom_score_adj: int = 0
    cpuset_cpus: str = ""
    cpuset_mems: str = ""
    unified: Dict[str, str] = dataclasses.field(default_factory=dict)

    def merge_from(self, other: Optional["LinuxContainerResources"]) -> None:
        """Non-zero fields of ``other`` win (the proxy's response merge)."""
        if other is None:
            return
        for f in dataclasses.fields(self):
            val = getattr(other, f.name)
            if f.name == "unified":
                self.unified.update(val)
            elif val:
                setattr(self, f.name, val)


@dataclasses.dataclass
class PodSandboxMetadata:
    name: str
    uid: str
    namespace: str = "default"
    attempt: int = 0


@dataclasses.dataclass
class PodSandboxHookRequest:
    pod_meta: PodSandboxMetadata
    runtime_handler: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    cgroup_parent: str = ""
    overhead: Optional[LinuxContainerResources] = None
    resources: Optional[LinuxContainerResources] = None


@dataclasses.dataclass
class PodSandboxHookResponse:
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    cgroup_parent: str = ""
    resources: Optional[LinuxContainerResources] = None


@dataclasses.dataclass
class ContainerMetadata:
    name: str
    id: str = ""
    attempt: int = 0


@dataclasses.dataclass
class ContainerResourceHookRequest:
    pod_meta: PodSandboxMetadata
    container_meta: ContainerMetadata
    container_annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    container_resources: Optional[LinuxContainerResources] = None
    pod_labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    pod_annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    pod_cgroup_parent: str = ""
    container_envs: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ContainerResourceHookResponse:
    container_annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    container_resources: Optional[LinuxContainerResources] = None
    pod_cgroup_parent: str = ""
    container_envs: Dict[str, str] = dataclasses.field(default_factory=dict)
