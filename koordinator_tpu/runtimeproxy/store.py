"""Pod/container checkpoint store.

Rebuild of ``pkg/runtimeproxy/store/manager.go``: the proxy checkpoints
every sandbox and container it has seen so later lifecycle calls (which
carry only ids in CRI) can reconstruct the hook request — and so a proxy
restart does not orphan in-flight pods. Checkpoints serialize to JSON on
disk when a path is configured, mirroring the reference's file-backed
store.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional

from .proto import (
    ContainerMetadata,
    ContainerResourceHookRequest,
    LinuxContainerResources,
    PodSandboxHookRequest,
    PodSandboxMetadata,
)


@dataclasses.dataclass
class PodSandboxInfo:
    request: PodSandboxHookRequest
    #: cgroup parent after hook merges — what the runtime actually used
    effective_cgroup_parent: str = ""


@dataclasses.dataclass
class ContainerInfo:
    pod_id: str
    request: ContainerResourceHookRequest


class Store:
    def __init__(self, checkpoint_path: Optional[str] = None):
        self.pods: Dict[str, PodSandboxInfo] = {}
        self.containers: Dict[str, ContainerInfo] = {}
        self.checkpoint_path = checkpoint_path
        if checkpoint_path and os.path.exists(checkpoint_path):
            self._load()

    def write_pod(self, pod_id: str, info: PodSandboxInfo) -> None:
        self.pods[pod_id] = info
        self._persist()

    def get_pod(self, pod_id: str) -> Optional[PodSandboxInfo]:
        return self.pods.get(pod_id)

    def delete_pod(self, pod_id: str) -> None:
        self.pods.pop(pod_id, None)
        self._persist()

    def write_container(self, container_id: str, info: ContainerInfo) -> None:
        self.containers[container_id] = info
        self._persist()

    def get_container(self, container_id: str) -> Optional[ContainerInfo]:
        return self.containers.get(container_id)

    def delete_container(self, container_id: str) -> None:
        self.containers.pop(container_id, None)
        self._persist()

    # ---- persistence ----

    def _persist(self) -> None:
        if not self.checkpoint_path:
            return
        payload = {
            "pods": {
                pid: {
                    "meta": dataclasses.asdict(info.request.pod_meta),
                    "labels": info.request.labels,
                    "annotations": info.request.annotations,
                    "cgroup_parent": info.request.cgroup_parent,
                    "effective_cgroup_parent": info.effective_cgroup_parent,
                }
                for pid, info in self.pods.items()
            },
            "containers": {
                cid: {
                    "pod_id": info.pod_id,
                    "pod_meta": dataclasses.asdict(info.request.pod_meta),
                    "container_meta": dataclasses.asdict(
                        info.request.container_meta
                    ),
                    "annotations": info.request.container_annotations,
                    "resources": dataclasses.asdict(info.request.container_resources)
                    if info.request.container_resources
                    else None,
                }
                for cid, info in self.containers.items()
            },
        }
        tmp = self.checkpoint_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.checkpoint_path)

    def _load(self) -> None:
        try:
            with open(self.checkpoint_path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return
        for pid, raw in payload.get("pods", {}).items():
            self.pods[pid] = PodSandboxInfo(
                request=PodSandboxHookRequest(
                    pod_meta=PodSandboxMetadata(**raw["meta"]),
                    labels=raw.get("labels", {}),
                    annotations=raw.get("annotations", {}),
                    cgroup_parent=raw.get("cgroup_parent", ""),
                ),
                effective_cgroup_parent=raw.get("effective_cgroup_parent", ""),
            )
        for cid, raw in payload.get("containers", {}).items():
            res = raw.get("resources")
            self.containers[cid] = ContainerInfo(
                pod_id=raw["pod_id"],
                request=ContainerResourceHookRequest(
                    pod_meta=PodSandboxMetadata(**raw["pod_meta"]),
                    container_meta=ContainerMetadata(**raw["container_meta"]),
                    container_annotations=raw.get("annotations", {}),
                    container_resources=LinuxContainerResources(**res)
                    if res
                    else None,
                ),
            )
