"""Hook dispatch with failure policies.

Rebuild of ``pkg/runtimeproxy/dispatcher/``: for a lifecycle point, call
every registered hook server that subscribed to it, in registration
order, folding each response into the accumulated one. A server error
under ``Fail`` policy aborts the CRI call; under ``Ignore``/``None`` the
request proceeds as if the hook had returned nothing
(``config.go:27-31``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .config import HookServerRegistration
from .proto import RuntimeHookType


class HookError(RuntimeError):
    """Raised to the CRI caller when a Fail-policy hook errors."""

    def __init__(self, server: str, hook: RuntimeHookType, cause: Exception):
        super().__init__(f"hook server {server} failed {hook.value}: {cause}")
        self.server = server
        self.hook = hook
        self.cause = cause


class Dispatcher:
    def __init__(self) -> None:
        self._servers: List[HookServerRegistration] = []

    def register(self, registration: HookServerRegistration) -> None:
        self._servers = [
            s for s in self._servers if s.name != registration.name
        ] + [registration]

    def unregister(self, name: str) -> None:
        self._servers = [s for s in self._servers if s.name != name]

    @property
    def servers(self) -> Tuple[HookServerRegistration, ...]:
        return tuple(self._servers)

    def dispatch(self, hook: RuntimeHookType, request) -> List[object]:
        """Responses from each subscribed server (errors under fails-open
        policies are dropped; a Fail-policy error raises HookError)."""
        responses: List[object] = []
        for server in self._servers:
            if hook not in server.hook_types:
                continue
            try:
                resp = server.handler(hook, request)
            except Exception as exc:  # noqa: BLE001 — policy decides
                from ..obs.errors import report_exception

                report_exception(
                    f"runtimeproxy.hook.{server.name}", exc
                )
                if not server.failure_policy.fails_open:
                    raise HookError(server.name, hook, exc) from exc
                continue
            if resp is not None:
                responses.append(resp)
        return responses
