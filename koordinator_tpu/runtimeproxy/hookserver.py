"""Koordlet-side RuntimeHookService implementation.

Rebuild of ``pkg/koordlet/runtimehooks/proxyserver/``: the hook-server end
of the proxy protocol. Each RPC reconstructs the pod from the request's
labels/annotations, renders the same pure hook plans as the NRI and
reconciler paths (:mod:`koordinator_tpu.koordlet.runtimehooks`), applies
cgroup writes through the serialized executor, and answers with the
spec-level adjustments (envs/annotations) the proxy merges into the CRI
request — one rendering, three delivery paths.
"""

from __future__ import annotations

import json
from typing import Optional

from ..api import extension as ext
from ..api.types import ObjectMeta, Pod, PodSpec
from ..koordlet import resourceexecutor as rex
from ..koordlet.runtimehooks import CpusetRule, pod_cgroup, pod_mutation, pod_plan
from .config import FailurePolicy, HookServerRegistration
from .proto import (
    ContainerResourceHookRequest,
    ContainerResourceHookResponse,
    PodSandboxHookRequest,
    PodSandboxHookResponse,
    RuntimeHookType,
)

#: annotation carrying flattened pod requests on the hook request (the
#: reference reconstructs these from the statesinformer; the wire path
#: keeps the hook server stateless for tests)
ANNOTATION_POD_REQUESTS = f"{ext.DOMAIN}/pod-requests"


def _pod_from(meta_name: str, uid: str, labels, annotations) -> Pod:
    requests = {}
    raw = annotations.get(ANNOTATION_POD_REQUESTS)
    if raw:
        try:
            requests = {k: float(v) for k, v in json.loads(raw).items()}
        except (ValueError, TypeError, AttributeError):
            requests = {}
    pod = Pod(
        meta=ObjectMeta(
            name=meta_name,
            uid=uid or meta_name,
            labels=dict(labels),
            annotations=dict(annotations),
        ),
        spec=PodSpec(requests=requests),
    )
    return pod


class KoordletHookServer:
    """Serves all seven RPCs; wire into a Dispatcher via :meth:`registration`."""

    def __init__(self, executor: rex.ResourceExecutor):
        self.executor = executor
        self.cpu_norm_ratio = 1.0
        #: shared-pool cpuset rule from the NodeResourceTopology report
        #: (set by whoever wires this server to the statesinformer)
        self.cpuset_rule: Optional[CpusetRule] = None

    def set_topology(self, topo) -> None:
        self.cpuset_rule = CpusetRule.from_topology(topo)

    def registration(
        self, failure_policy: FailurePolicy = FailurePolicy.NONE
    ) -> HookServerRegistration:
        return HookServerRegistration.create(
            name="koordlet",
            hook_types=tuple(RuntimeHookType),
            handler=self.handle,
            failure_policy=failure_policy,
        )

    def handle(self, hook: RuntimeHookType, request):
        if isinstance(request, PodSandboxHookRequest):
            return self._handle_sandbox(hook, request)
        if isinstance(request, ContainerResourceHookRequest):
            return self._handle_container(hook, request)
        return None

    def _handle_sandbox(
        self, hook: RuntimeHookType, request: PodSandboxHookRequest
    ) -> Optional[PodSandboxHookResponse]:
        pod = _pod_from(
            request.pod_meta.name,
            request.pod_meta.uid,
            request.labels,
            request.annotations,
        )
        if hook is RuntimeHookType.PRE_RUN_POD_SANDBOX:
            self.executor.apply(
                pod_plan(pod, self.cpu_norm_ratio, self.cpuset_rule),
                reason="proxy:PreRunPodSandbox",
            )
            return PodSandboxHookResponse(
                annotations={ext.LABEL_POD_QOS: pod.qos.name}
            )
        if hook is RuntimeHookType.POST_STOP_POD_SANDBOX:
            # resource GC: the reference removes the pod's cgroup-level
            # knobs; the executor's audit keeps the trail
            self.executor.gc_group(
                pod_cgroup(pod), reason="proxy:PostStopPodSandbox"
            )
            return PodSandboxHookResponse()
        return None

    def _handle_container(
        self, hook: RuntimeHookType, request: ContainerResourceHookRequest
    ) -> Optional[ContainerResourceHookResponse]:
        pod = _pod_from(
            request.pod_meta.name,
            request.pod_meta.uid,
            request.pod_labels,
            request.pod_annotations,
        )
        if hook in (
            RuntimeHookType.PRE_CREATE_CONTAINER,
            RuntimeHookType.PRE_START_CONTAINER,
        ):
            mutation = pod_mutation(pod)
            return ContainerResourceHookResponse(
                container_envs=dict(mutation.env)
            )
        if hook is RuntimeHookType.PRE_UPDATE_CONTAINER_RESOURCES:
            self.executor.apply(
                pod_plan(pod, self.cpu_norm_ratio, self.cpuset_rule),
                reason="proxy:PreUpdateContainerResources",
            )
            return ContainerResourceHookResponse()
        return None
