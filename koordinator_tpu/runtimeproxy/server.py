"""CRI interposer: kubelet → proxy → backend runtime.

Rebuild of ``pkg/runtimeproxy/server/cri/`` (``criserver.go:88``,
``runtime.go:32-40``): every intercepted CRI call builds a hook request
(from the call + the checkpoint store), dispatches it to the registered
hook servers, merges their responses into the forwarded request
(labels/annotations/cgroup parent/resources/envs — the proto's documented
merge), then calls the backend runtime. Post-hooks run after the backend
returns. The backend is injectable; production wires a CRI gRPC client,
tests a fake (the reference's resexecutor/cri|docker split).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Protocol

from .dispatcher import Dispatcher
from .proto import (
    ContainerMetadata,
    ContainerResourceHookRequest,
    ContainerResourceHookResponse,
    LinuxContainerResources,
    PodSandboxHookRequest,
    PodSandboxHookResponse,
    PodSandboxMetadata,
    RuntimeHookType,
)
from .store import ContainerInfo, PodSandboxInfo, Store


# ---- minimal CRI request shapes (the fields the proxy touches) ----


@dataclasses.dataclass
class PodSandboxConfig:
    metadata: PodSandboxMetadata
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    cgroup_parent: str = ""


@dataclasses.dataclass
class ContainerConfig:
    metadata: ContainerMetadata
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    envs: Dict[str, str] = dataclasses.field(default_factory=dict)
    resources: LinuxContainerResources = dataclasses.field(
        default_factory=LinuxContainerResources
    )


class BackendRuntime(Protocol):
    """The real CRI runtime behind the proxy (containerd in the
    reference; a fake in tests)."""

    def run_pod_sandbox(self, config: PodSandboxConfig) -> str: ...
    def stop_pod_sandbox(self, pod_id: str) -> None: ...
    def create_container(self, pod_id: str, config: ContainerConfig) -> str: ...
    def start_container(self, container_id: str) -> None: ...
    def stop_container(self, container_id: str) -> None: ...
    def update_container_resources(
        self, container_id: str, resources: LinuxContainerResources
    ) -> None: ...


class CRIProxy:
    """The man-in-the-middle server (one instance per runtime socket)."""

    def __init__(
        self,
        backend: BackendRuntime,
        dispatcher: Optional[Dispatcher] = None,
        store: Optional[Store] = None,
    ):
        self.backend = backend
        self.dispatcher = dispatcher or Dispatcher()
        self.store = store or Store()

    # ---- sandbox lifecycle ----

    def run_pod_sandbox(self, config: PodSandboxConfig) -> str:
        request = PodSandboxHookRequest(
            pod_meta=config.metadata,
            labels=dict(config.labels),
            annotations=dict(config.annotations),
            cgroup_parent=config.cgroup_parent,
        )
        for resp in self.dispatcher.dispatch(
            RuntimeHookType.PRE_RUN_POD_SANDBOX, request
        ):
            self._merge_sandbox(config, resp)
        pod_id = self.backend.run_pod_sandbox(config)
        self.store.write_pod(
            pod_id,
            PodSandboxInfo(
                request=request, effective_cgroup_parent=config.cgroup_parent
            ),
        )
        return pod_id

    def stop_pod_sandbox(self, pod_id: str) -> None:
        self.backend.stop_pod_sandbox(pod_id)
        info = self.store.get_pod(pod_id)
        if info is not None:
            # post-hook: resource GC after the sandbox is gone
            self.dispatcher.dispatch(
                RuntimeHookType.POST_STOP_POD_SANDBOX, info.request
            )
        self.store.delete_pod(pod_id)

    # ---- container lifecycle ----

    def _container_request(
        self, pod_id: str, config: ContainerConfig
    ) -> ContainerResourceHookRequest:
        pod = self.store.get_pod(pod_id)
        return ContainerResourceHookRequest(
            pod_meta=pod.request.pod_meta
            if pod
            else PodSandboxMetadata(name="", uid=pod_id),
            container_meta=config.metadata,
            container_annotations=dict(config.annotations),
            container_resources=config.resources,
            pod_labels=dict(pod.request.labels) if pod else {},
            pod_annotations=dict(pod.request.annotations) if pod else {},
            pod_cgroup_parent=pod.effective_cgroup_parent if pod else "",
            container_envs=dict(config.envs),
        )

    def create_container(self, pod_id: str, config: ContainerConfig) -> str:
        request = self._container_request(pod_id, config)
        for resp in self.dispatcher.dispatch(
            RuntimeHookType.PRE_CREATE_CONTAINER, request
        ):
            self._merge_container(config, resp)
        container_id = self.backend.create_container(pod_id, config)
        config.metadata.id = container_id
        request.container_meta = config.metadata
        self.store.write_container(
            container_id, ContainerInfo(pod_id=pod_id, request=request)
        )
        return container_id

    def start_container(self, container_id: str) -> None:
        info = self.store.get_container(container_id)
        if info is not None:
            self.dispatcher.dispatch(
                RuntimeHookType.PRE_START_CONTAINER, info.request
            )
        self.backend.start_container(container_id)
        if info is not None:
            self.dispatcher.dispatch(
                RuntimeHookType.POST_START_CONTAINER, info.request
            )

    def stop_container(self, container_id: str) -> None:
        self.backend.stop_container(container_id)
        info = self.store.get_container(container_id)
        if info is not None:
            self.dispatcher.dispatch(
                RuntimeHookType.POST_STOP_CONTAINER, info.request
            )
        self.store.delete_container(container_id)

    def update_container_resources(
        self, container_id: str, resources: LinuxContainerResources
    ) -> None:
        info = self.store.get_container(container_id)
        if info is not None:
            request = dataclasses.replace(
                info.request, container_resources=resources
            )
            for resp in self.dispatcher.dispatch(
                RuntimeHookType.PRE_UPDATE_CONTAINER_RESOURCES, request
            ):
                if isinstance(resp, ContainerResourceHookResponse):
                    resources.merge_from(resp.container_resources)
            info.request.container_resources = resources
            self.store.write_container(container_id, info)
        self.backend.update_container_resources(container_id, resources)

    # ---- response merges (api.proto's documented semantics) ----

    @staticmethod
    def _merge_sandbox(config: PodSandboxConfig, resp: object) -> None:
        if not isinstance(resp, PodSandboxHookResponse):
            return
        config.labels.update(resp.labels)
        config.annotations.update(resp.annotations)
        if resp.cgroup_parent:
            config.cgroup_parent = resp.cgroup_parent

    @staticmethod
    def _merge_container(config: ContainerConfig, resp: object) -> None:
        if not isinstance(resp, ContainerResourceHookResponse):
            return
        config.annotations.update(resp.container_annotations)
        config.envs.update(resp.container_envs)
        config.resources.merge_from(resp.container_resources)
