"""Hook server registration + failure policies.

Rebuild of ``pkg/runtimeproxy/config/`` (``config.go:24-66``): each hook
server registers which CRI lifecycle points it wants and what happens when
it errors — ``Fail`` propagates the error to kubelet, ``Ignore`` (and the
unset default ``None``) forwards the original request untouched.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, FrozenSet, Iterable

from .proto import RuntimeHookType


class FailurePolicy(enum.Enum):
    FAIL = "Fail"
    IGNORE = "Ignore"
    NONE = ""       # treated as Ignore (config.go:31)

    @property
    def fails_open(self) -> bool:
        return self is not FailurePolicy.FAIL


def parse_failure_policy(raw: str) -> FailurePolicy:
    """config.go:35-43 GetFailurePolicyType (unknown values are errors
    there; here they normalize to NONE to keep registration total)."""
    for policy in FailurePolicy:
        if policy.value == raw:
            return policy
    return FailurePolicy.NONE


@dataclasses.dataclass(frozen=True)
class HookServerRegistration:
    """One registered hook server: a name, the lifecycle points it
    subscribes to, its failure policy, and the handler callable
    ``(RuntimeHookType, request) -> response | None``."""

    name: str
    hook_types: FrozenSet[RuntimeHookType]
    handler: Callable
    failure_policy: FailurePolicy = FailurePolicy.NONE

    @staticmethod
    def create(
        name: str,
        hook_types: Iterable[RuntimeHookType],
        handler: Callable,
        failure_policy: FailurePolicy = FailurePolicy.NONE,
    ) -> "HookServerRegistration":
        return HookServerRegistration(
            name=name,
            hook_types=frozenset(hook_types),
            handler=handler,
            failure_policy=failure_policy,
        )
