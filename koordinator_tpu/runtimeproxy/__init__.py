"""CRI runtime proxy: kubelet ↔ hook servers ↔ backend runtime.

Rebuild of ``pkg/runtimeproxy/`` + ``apis/runtime/v1alpha1/api.proto``
(SURVEY §2.6). See :mod:`server` for the interposer, :mod:`hookserver`
for the koordlet-side RuntimeHookService implementation.
"""

from .config import FailurePolicy, HookServerRegistration, parse_failure_policy
from .dispatcher import Dispatcher, HookError
from .hookserver import KoordletHookServer
from .proto import (
    ContainerMetadata,
    ContainerResourceHookRequest,
    ContainerResourceHookResponse,
    LinuxContainerResources,
    PodSandboxHookRequest,
    PodSandboxHookResponse,
    PodSandboxMetadata,
    RuntimeHookType,
)
from .server import ContainerConfig, CRIProxy, PodSandboxConfig
from .store import ContainerInfo, PodSandboxInfo, Store

__all__ = [
    "ContainerConfig",
    "ContainerInfo",
    "ContainerMetadata",
    "ContainerResourceHookRequest",
    "ContainerResourceHookResponse",
    "CRIProxy",
    "Dispatcher",
    "FailurePolicy",
    "HookError",
    "HookServerRegistration",
    "KoordletHookServer",
    "LinuxContainerResources",
    "parse_failure_policy",
    "PodSandboxConfig",
    "PodSandboxHookRequest",
    "PodSandboxHookResponse",
    "PodSandboxInfo",
    "PodSandboxMetadata",
    "RuntimeHookType",
    "Store",
]
