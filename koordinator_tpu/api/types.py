"""Core API object model (the rebuild's "CRDs").

Lightweight dataclasses standing in for the reference's CRD Go types under
``apis/`` (reference: ``apis/slo/v1alpha1/nodemetric_types.go``,
``apis/scheduling/v1alpha1/reservation_types.go``, ``device_types.go:104``,
``pod_migration_job_types.go:27-40``, thirdparty ElasticQuota/PodGroup).

These objects are the *host-side* representation; the solver never sees them.
``core.snapshot.SnapshotBuilder`` lowers them into dense arrays once, and all
hot-path decisions happen on tensors.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from .extension import (
    DEFAULT_RESOURCES,
    LABEL_POD_QOS,
    PriorityClass,
    QoSClass,
    qos_for_priority,
)

ResourceList = Dict[str, float]


def _res(d: Optional[Mapping[str, float]]) -> ResourceList:
    return dict(d) if d else {}


@dataclasses.dataclass
class ObjectMeta:
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: controlling workload's UID (k8s ownerReferences controller=true);
    #: "" = no controller (bare pod)
    owner_uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class PodSpec:
    """Flattened pod scheduling spec.

    ``requests``/``limits`` use snapshot units: cpu in milli-cores, memory in
    MiB, extended resources in their native integer unit.
    """

    requests: ResourceList = dataclasses.field(default_factory=dict)
    limits: ResourceList = dataclasses.field(default_factory=dict)
    #: explicit usage estimate overriding the estimator's request scaling
    #: (reference estimator framework, loadaware/estimator/estimator.go:
    #: the default estimator scales requests, but callers with a measured
    #: profile — e.g. the control plane's PendingPod.estimated — pass it)
    estimated: Optional[ResourceList] = None
    priority: Optional[int] = None
    scheduler_name: str = "koord-scheduler"
    node_name: Optional[str] = None
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    affinity_required_nodes: Optional[Sequence[str]] = None  # simplified nodeAffinity


class PodPhase(enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class Pod:
    meta: ObjectMeta
    spec: PodSpec = dataclasses.field(default_factory=PodSpec)
    phase: PodPhase = PodPhase.PENDING

    @property
    def qos(self) -> QoSClass:
        explicit = QoSClass.parse(self.meta.labels.get(LABEL_POD_QOS))
        if explicit is not QoSClass.NONE:
            return explicit
        return qos_for_priority(self.priority_class)

    @property
    def priority_class(self) -> PriorityClass:
        return PriorityClass.from_priority(self.spec.priority)


@dataclasses.dataclass
class NodeStatus:
    allocatable: ResourceList = dataclasses.field(default_factory=dict)
    capacity: ResourceList = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Node:
    meta: ObjectMeta
    status: NodeStatus = dataclasses.field(default_factory=NodeStatus)
    unschedulable: bool = False


# --- slo.koordinator.sh/NodeMetric (nodemetric_types.go) ---

#: aggregation percentile keys reported by the node agent
AGG_P50, AGG_P90, AGG_P95, AGG_P99 = "p50", "p90", "p95", "p99"
AGG_TYPES = (AGG_P50, AGG_P90, AGG_P95, AGG_P99)


@dataclasses.dataclass
class ResourceMetric:
    usage: ResourceList = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PodMetricInfo:
    namespace: str
    name: str
    usage: ResourceList = dataclasses.field(default_factory=dict)
    priority_class: PriorityClass = PriorityClass.NONE


@dataclasses.dataclass
class NodeMetric:
    """Node + pod usage report (reference ``nodemetric_types.go``).

    ``aggregated`` maps percentile key → usage over the aggregation window;
    ``prod_usage`` mirrors the reference's SystemUsage+ProdUsage split used by
    LoadAware's prod-usage thresholds.
    """

    meta: ObjectMeta
    node_usage: ResourceMetric = dataclasses.field(default_factory=ResourceMetric)
    prod_usage: ResourceMetric = dataclasses.field(default_factory=ResourceMetric)
    sys_usage: ResourceMetric = dataclasses.field(default_factory=ResourceMetric)
    aggregated: Dict[str, ResourceMetric] = dataclasses.field(default_factory=dict)
    pod_metrics: List[PodMetricInfo] = dataclasses.field(default_factory=list)
    update_time: float = dataclasses.field(default_factory=time.time)
    report_interval_s: float = 60.0  # states_nodemetric.go:61-66
    aggregate_window_s: float = 300.0

    def expired(self, now: float, expiry_s: float = 180.0) -> bool:
        """LoadAware degrades to request-based estimation when the metric is
        stale (reference ``load_aware.go:143-149``)."""
        return (now - self.update_time) > expiry_s


# --- scheduling.koordinator.sh/Reservation (reservation_types.go) ---


class ReservationPhase(enum.Enum):
    PENDING = "Pending"
    AVAILABLE = "Available"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class ReservationOwner:
    """Owner matching: label selector and/or controller reference."""

    label_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    namespace: Optional[str] = None


#: reservation allocate policies (reference
#: ``apis/scheduling/v1alpha1/reservation_types.go:78-97``): "" (Default,
#: deprecated — treated as Aligned) / Aligned: the pod allocates from the
#: reservation FIRST and may spill to node free capacity; Restricted:
#: resources the reservation declares may ONLY come from the reservation
#: (undeclared dims still allocate from the node)
RESERVATION_ALLOCATE_POLICY_ALIGNED = "Aligned"
RESERVATION_ALLOCATE_POLICY_RESTRICTED = "Restricted"


@dataclasses.dataclass
class Reservation:
    meta: ObjectMeta
    requests: ResourceList = dataclasses.field(default_factory=dict)
    owners: List[ReservationOwner] = dataclasses.field(default_factory=list)
    allocate_once: bool = True
    ttl_s: Optional[float] = None
    phase: ReservationPhase = ReservationPhase.PENDING
    node_name: Optional[str] = None          # set once scheduled
    allocated: ResourceList = dataclasses.field(default_factory=dict)
    current_owners: List[str] = dataclasses.field(default_factory=list)  # pod uids
    available_time: Optional[float] = None   # when it became Available (TTL base)
    #: "" | "Aligned" | "Restricted" (reservation_types.go:78-97)
    allocate_policy: str = ""


# --- scheduling.koordinator.sh/Device (device_types.go:104) ---


@dataclasses.dataclass
class DeviceInfo:
    dev_type: str               # "gpu" | "rdma"
    minor: int
    resources: ResourceList = dataclasses.field(default_factory=dict)
    numa_node: int = -1
    pcie_bus: str = ""
    #: SR-IOV virtual-function bus IDs exposed by this device (reference
    #: ``apis/extension/device_share.go:126-139`` VirtualFunctions): a NIC
    #: with VFs is shared across pods VF-by-VF, never allocated whole
    vfs: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class GPUPartition:
    """One interconnect-complete GPU group (reference
    ``apis/extension/device_share.go:217`` ``GPUPartition``): the minors
    share a link domain (NVLink analog; for TPU hosts, an ICI ring), and
    multi-device allocations should land entirely inside one partition."""

    minors: List[int]
    link_type: str = "NVLink"
    ring_bus_bandwidth: float = 0.0     # GB/s; 0 = unspecified
    allocation_score: int = 1

    @property
    def minors_mask(self) -> int:
        m = 0
        for minor in self.minors:
            m |= 1 << minor
        return m


@dataclasses.dataclass
class Device:
    """Per-node device inventory reported by the node agent."""

    meta: ObjectMeta            # name == node name
    devices: List[DeviceInfo] = dataclasses.field(default_factory=list)
    #: size -> partitions of exactly that many minors (reference
    #: ``GPUPartitionTable``, annotated on the Device CR)
    partitions: Dict[int, List[GPUPartition]] = dataclasses.field(
        default_factory=dict
    )
    #: "Honor" (partition table is binding) | "Prefer" (fall back to
    #: topology packing when no partition fits) | "" (ignore table)
    partition_policy: str = ""


@dataclasses.dataclass
class TopologyZone:
    """One NUMA zone of a node (external NodeResourceTopology CRD,
    ``k8stopologyawareschedwg``; reported by koordlet's
    ``statesinformer/impl/states_noderesourcetopology.go``)."""

    name: str                   # e.g. "node-0"
    zone_type: str = "Node"
    allocatable: ResourceList = dataclasses.field(default_factory=dict)
    capacity: ResourceList = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class NodeResourceTopology:
    """Per-node NUMA topology + kubelet CPU-manager state report.

    ``cpu_topology`` maps logical cpu id → (core, numa node, socket);
    ``kubelet_reserved_cpus`` mirrors the kubelet cpu-manager state the
    reference reads back through annotations
    (``statesinformer/impl/states_noderesourcetopology.go``).
    """

    meta: ObjectMeta            # name == node name
    zones: List[TopologyZone] = dataclasses.field(default_factory=list)
    cpu_topology: Dict[int, Tuple[int, int, int]] = dataclasses.field(
        default_factory=dict
    )
    kubelet_reserved_cpus: List[int] = dataclasses.field(default_factory=list)
    topology_policy: str = "None"


# --- thirdparty PodGroup (gang) ---


@dataclasses.dataclass
class PodGroup:
    meta: ObjectMeta
    min_member: int = 1
    total_member: Optional[int] = None
    schedule_timeout_s: float = 600.0


# --- thirdparty ElasticQuota ---


@dataclasses.dataclass
class ElasticQuota:
    meta: ObjectMeta
    min: ResourceList = dataclasses.field(default_factory=dict)
    max: ResourceList = dataclasses.field(default_factory=dict)
    shared_weight: ResourceList = dataclasses.field(default_factory=dict)
    parent: str = ""            # quota tree edge (label quota.scheduling.../parent)
    is_parent: bool = False
    tree_id: str = ""
    #: tree-root marker (label quota.scheduling.../is-root); a root quota with a
    #: tree-id carries the tree's total capacity (annotation .../total-resource)
    is_root: bool = False
    total_resource: ResourceList = dataclasses.field(default_factory=dict)
    #: when true, a tree root's capacity is NOT deducted from the default tree
    ignore_default_tree: bool = False
    #: when False, the quota's unused min is NEVER lent to siblings — the
    #: full min stays reserved regardless of demand (reference label
    #: ``quota.scheduling.koordinator.sh/allow-lent-resource``, quotaNode
    #: AllowLentResource; default true)
    allow_lent_resource: bool = True


# --- scheduling.koordinator.sh/PodMigrationJob (pod_migration_job_types.go:27-40) ---


class MigrationPhase(enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


class MigrationMode(enum.Enum):
    RESERVATION_FIRST = "ReservationFirst"
    EVICT_DIRECTLY = "EvictDirectly"


@dataclasses.dataclass
class PodMigrationJob:
    meta: ObjectMeta
    pod_uid: str = ""
    mode: MigrationMode = MigrationMode.RESERVATION_FIRST
    phase: MigrationPhase = MigrationPhase.PENDING
    reservation_name: Optional[str] = None
    reason: str = ""
    create_time: float = dataclasses.field(default_factory=time.time)


# --- quota.koordinator.sh/ElasticQuotaProfile ---


@dataclasses.dataclass
class ElasticQuotaProfile:
    """Quota tree generator (reference
    ``apis/quota/v1alpha1/elastic_quota_profile_types.go`` + reconciler
    ``pkg/quota-controller/profile/``): selects a set of nodes by label and
    maintains a root ElasticQuota whose min/max track the selected nodes'
    total allocatable."""

    meta: ObjectMeta
    quota_name: str = ""
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    quota_labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: resource dims to sum over selected nodes; empty = all reported
    resource_keys: List[str] = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.quota_name:
            self.quota_name = self.meta.name


# --- analysis.koordinator.sh/Recommendation ---


@dataclasses.dataclass
class Recommendation:
    """Resource recommendation scaffold (reference
    ``apis/analysis/v1alpha1/recommendation_types.go``): target workload +
    the p95-peak resource estimate produced from prediction histograms."""

    meta: ObjectMeta
    workload_kind: str = "Deployment"
    workload_name: str = ""
    recommended: ResourceList = dataclasses.field(default_factory=dict)
    update_time: float = dataclasses.field(default_factory=time.time)


# --- config.koordinator.sh/ClusterColocationProfile ---


@dataclasses.dataclass
class ClusterColocationProfile:
    """Admission-time pod mutation profile (reference
    ``cluster_colocation_profile_types.go`` + webhook
    ``pod/mutating/cluster_colocation_profile.go``)."""

    meta: ObjectMeta
    selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    namespace_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    qos_class: Optional[QoSClass] = None
    priority: Optional[int] = None
    scheduler_name: Optional[str] = None
    #: resource name rewrite map, e.g. cpu -> kubernetes.io/batch-cpu
    resource_translation: Dict[str, str] = dataclasses.field(default_factory=dict)


# --- slo.koordinator.sh/NodeSLO (nodeslo_types.go) ---


@dataclasses.dataclass
class ResourceThresholdStrategy:
    """Per-node BE suppression thresholds (reference
    ``apis/slo/v1alpha1/nodeslo_types.go`` ResourceThresholdStrategy)."""

    enable: bool = False
    cpu_suppress_threshold_percent: float = 65.0
    cpu_evict_be_usage_threshold_percent: float = 90.0
    memory_evict_threshold_percent: float = 70.0
    memory_evict_lower_percent: Optional[float] = None


@dataclasses.dataclass
class SystemStrategy:
    """Node-level kernel tuning (nodeslo_types.go SystemStrategy →
    koordlet sysreconcile strategy)."""

    enable: bool = False
    min_free_kbytes_factor: float = 100.0   # per-mille of total memory
    watermark_scale_factor: float = 150.0
    memcg_reap_background: int = 0


@dataclasses.dataclass
class ResctrlStrategy:
    """RDT L3/MB partitioning per QoS tier (nodeslo_types.go ResourceQOS
    resctrlQOS → koordlet resctrl strategy + qosmanager/resctrl)."""

    enable: bool = False
    #: percent of LLC ways each tier may use
    llc_percent: Dict[QoSClass, float] = dataclasses.field(
        default_factory=lambda: {
            QoSClass.LSR: 100.0,
            QoSClass.LS: 100.0,
            QoSClass.BE: 30.0,
        }
    )
    #: percent of memory bandwidth each tier may use
    mba_percent: Dict[QoSClass, float] = dataclasses.field(
        default_factory=lambda: {
            QoSClass.LSR: 100.0,
            QoSClass.LS: 100.0,
            QoSClass.BE: 100.0,
        }
    )


@dataclasses.dataclass
class BlkIOStrategy:
    """Block IO throttles per tier (nodeslo_types.go blkioQOS →
    qosmanager blkio strategy). Limits are bytes/s or IOs/s; 0 = no limit."""

    enable: bool = False
    be_read_bps: int = 0
    be_write_bps: int = 0
    be_read_iops: int = 0
    be_write_iops: int = 0


@dataclasses.dataclass
class CPUBurstStrategy:
    policy: str = "none"        # none|cpuBurstOnly|cfsQuotaBurstOnly|auto
    cpu_burst_percent: float = 1000.0
    cfs_quota_burst_percent: float = 300.0


@dataclasses.dataclass
class NodeSLO:
    meta: ObjectMeta            # name == node name
    threshold: ResourceThresholdStrategy = dataclasses.field(
        default_factory=ResourceThresholdStrategy
    )
    cpu_burst: CPUBurstStrategy = dataclasses.field(default_factory=CPUBurstStrategy)
    #: per-QoS-class resource QoS knobs, keyed by QoSClass
    resource_qos: Dict[QoSClass, Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )
    system: SystemStrategy = dataclasses.field(
        default_factory=lambda: SystemStrategy()
    )
    resctrl: ResctrlStrategy = dataclasses.field(
        default_factory=lambda: ResctrlStrategy()
    )
    blkio: BlkIOStrategy = dataclasses.field(
        default_factory=lambda: BlkIOStrategy()
    )
    #: out-of-band host daemons: (name, cgroup dir, qos class name)
    host_applications: List[Tuple[str, str, str]] = dataclasses.field(
        default_factory=list
    )


__all__ = [
    "AGG_TYPES",
    "AGG_P50",
    "AGG_P90",
    "AGG_P95",
    "AGG_P99",
    "ClusterColocationProfile",
    "CPUBurstStrategy",
    "Device",
    "DeviceInfo",
    "ElasticQuota",
    "ElasticQuotaProfile",
    "Recommendation",
    "MigrationMode",
    "MigrationPhase",
    "Node",
    "NodeMetric",
    "NodeSLO",
    "NodeStatus",
    "ObjectMeta",
    "Pod",
    "PodGroup",
    "PodMetricInfo",
    "PodMigrationJob",
    "PodPhase",
    "PodSpec",
    "Reservation",
    "ReservationOwner",
    "ReservationPhase",
    "ResourceMetric",
    "ResourceThresholdStrategy",
    "ResourceList",
]
