"""Koordinator-format YAML ingestion: manifests → ``api.types`` objects.

The reference is driven by YAML (CRDs under ``config/crd/bases/``, demos
under ``examples/spark-jobs/`` — e.g.
``cluster-colocation-profile.yaml``); this module is the rebuild's front
door for the same wire format: multi-document YAML in, typed objects out,
dispatched by (apiVersion, kind). Resource quantities normalize to
snapshot units (cpu → milli-cores, memory → MiB, extended resources
native), matching ``PodSpec``'s documented convention.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Mapping, Optional, Tuple

from . import extension as ext
from .types import (
    ClusterColocationProfile,
    Device,
    DeviceInfo,
    ElasticQuota,
    ElasticQuotaProfile,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
    Reservation,
    ReservationOwner,
)

#: well-known PriorityClass names → default priority values (reference
#: ``apis/extension/priority.go:29-48`` band bases; the classes ship as
#: PriorityClass objects with these values)
PRIORITY_CLASS_VALUES = {
    "koord-prod": 9000,
    "koord-mid": 7000,
    "koord-batch": 5000,
    "koord-free": 3000,
}

_QUANTITY_RE = re.compile(r"^([0-9.]+)([a-zA-Z]*)$")
_BINARY = {"Ki": 1 << 10, "Mi": 1 << 20, "Gi": 1 << 30, "Ti": 1 << 40}
_DECIMAL = {"k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "m": 1e-3, "": 1.0}


def parse_quantity(value) -> float:
    """k8s resource.Quantity → float base units ("500m" → 0.5,
    "2Gi" → 2147483648, "1" → 1.0)."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _QUANTITY_RE.match(str(value).strip())
    if not m:
        raise ValueError(f"malformed quantity: {value!r}")
    num, suffix = float(m.group(1)), m.group(2)
    if suffix in _BINARY:
        return num * _BINARY[suffix]
    if suffix in _DECIMAL:
        return num * _DECIMAL[suffix]
    raise ValueError(f"unknown quantity suffix: {value!r}")


def _cpu_milli(value) -> float:
    return parse_quantity(value) * 1000.0


def _mem_mib(value) -> float:
    return parse_quantity(value) / (1 << 20)


def convert_resource_list(rl: Mapping) -> Dict[str, float]:
    """k8s ResourceList → snapshot units (cpu milli, memory MiB,
    batch-cpu already milli-denominated, everything else native)."""
    out: Dict[str, float] = {}
    for name, raw in (rl or {}).items():
        if name == ext.RES_CPU:
            out[name] = _cpu_milli(raw)
        elif name == ext.RES_MEMORY:
            out[name] = _mem_mib(raw)
        elif name == ext.RES_BATCH_MEMORY:
            out[name] = _mem_mib(raw)
        elif name in (ext.RES_BATCH_CPU,):
            # batch-cpu is milli-denominated on the wire (resource.go)
            out[name] = parse_quantity(raw)
        else:
            out[name] = parse_quantity(raw)
    return out


@dataclasses.dataclass
class NamespaceInfo:
    """v1/Namespace — carried for profile namespaceSelector matching."""

    name: str
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


def _meta(doc: Mapping) -> ObjectMeta:
    md = doc.get("metadata") or {}
    return ObjectMeta(
        name=str(md.get("name", "")),
        namespace=str(md.get("namespace", "default")),
        labels={str(k): str(v) for k, v in (md.get("labels") or {}).items()},
        annotations={
            str(k): str(v) for k, v in (md.get("annotations") or {}).items()
        },
    )


def _pod(doc: Mapping) -> Pod:
    meta = _meta(doc)
    spec = doc.get("spec") or {}
    requests: Dict[str, float] = {}
    limits: Dict[str, float] = {}
    for c in spec.get("containers") or []:
        res = c.get("resources") or {}
        for k, v in convert_resource_list(res.get("requests") or {}).items():
            requests[k] = requests.get(k, 0.0) + v
        for k, v in convert_resource_list(res.get("limits") or {}).items():
            limits[k] = limits.get(k, 0.0) + v
    # Kubernetes effective pod requests: max(each initContainer,
    # sum(containers)) per resource, plus spec.overhead (advisor r4 —
    # an init container larger than the main containers must gate
    # placement or the pod can land where it cannot start)
    for c in spec.get("initContainers") or []:
        res = c.get("resources") or {}
        for k, v in convert_resource_list(res.get("requests") or {}).items():
            if v > requests.get(k, 0.0):
                requests[k] = v
        for k, v in convert_resource_list(res.get("limits") or {}).items():
            if v > limits.get(k, 0.0):
                limits[k] = v
    for k, v in convert_resource_list(spec.get("overhead") or {}).items():
        requests[k] = requests.get(k, 0.0) + v
    priority = spec.get("priority")
    if priority is None:
        priority = PRIORITY_CLASS_VALUES.get(spec.get("priorityClassName", ""))
    pod_spec = PodSpec(
        requests=requests,
        limits=limits,
        priority=priority,
        node_name=spec.get("nodeName"),
        node_selector={
            str(k): str(v)
            for k, v in (spec.get("nodeSelector") or {}).items()
        },
    )
    if spec.get("schedulerName"):
        pod_spec.scheduler_name = str(spec["schedulerName"])
    return Pod(meta=meta, spec=pod_spec)


def _node(doc: Mapping) -> Node:
    meta = _meta(doc)
    status = doc.get("status") or {}
    return Node(
        meta=meta,
        status=NodeStatus(
            allocatable=convert_resource_list(status.get("allocatable") or {}),
            capacity=convert_resource_list(status.get("capacity") or {}),
        ),
    )


def _profile(doc: Mapping) -> ClusterColocationProfile:
    spec = doc.get("spec") or {}
    qos = spec.get("qosClass")
    priority = spec.get("koordinatorPriority")
    prio_class = spec.get("priorityClassName", "")
    labels = {
        str(k): str(v) for k, v in (spec.get("labels") or {}).items()
    }
    if prio_class:
        # the reference profile sets the k8s PriorityClass; the priority
        # VALUE that matters for banding comes from the class table
        labels.setdefault(ext.LABEL_POD_PRIORITY_CLASS, prio_class)
    base = PRIORITY_CLASS_VALUES.get(prio_class)
    eff_priority = None
    if base is not None:
        # koordinatorPriority is the intra-band sub-priority (reference
        # LabelPodPriority); the scheduling priority stays the band base
        eff_priority = base
    if priority is not None:
        labels[ext.LABEL_POD_PRIORITY] = str(priority)
    translation = {}
    if qos == "BE" or prio_class == "koord-batch":
        # batch-tier profiles run pods on the overcommitted batch
        # resources (how Spark pods become BE — the webhook rewrites
        # requests to kubernetes.io/batch-*)
        translation = {
            ext.RES_CPU: ext.RES_BATCH_CPU,
            ext.RES_MEMORY: ext.RES_BATCH_MEMORY,
        }
    return ClusterColocationProfile(
        meta=_meta(doc),
        selector={
            str(k): str(v)
            for k, v in (
                (spec.get("selector") or {}).get("matchLabels") or {}
            ).items()
        },
        namespace_selector={
            str(k): str(v)
            for k, v in (
                (spec.get("namespaceSelector") or {}).get("matchLabels") or {}
            ).items()
        },
        labels=labels,
        annotations={
            str(k): str(v) for k, v in (spec.get("annotations") or {}).items()
        },
        qos_class=ext.QoSClass.parse(qos) if qos else None,
        priority=eff_priority,
        scheduler_name=spec.get("schedulerName"),
        resource_translation=translation,
    )


def _reservation(doc: Mapping) -> Reservation:
    spec = doc.get("spec") or {}
    template = (spec.get("template") or {}).get("spec") or {}
    requests: Dict[str, float] = {}
    for c in template.get("containers") or []:
        res = c.get("resources") or {}
        for k, v in convert_resource_list(res.get("requests") or {}).items():
            requests[k] = requests.get(k, 0.0) + v
    owners = []
    for o in spec.get("owners") or []:
        sel = (o.get("labelSelector") or {}).get("matchLabels") or {}
        owners.append(
            ReservationOwner(
                label_selector={str(k): str(v) for k, v in sel.items()},
                namespace=(o.get("object") or {}).get("namespace"),
            )
        )
    ttl = spec.get("ttl")
    ttl_s = None
    if ttl:
        m = re.match(r"^(\d+)([smh])$", str(ttl))
        if m:
            ttl_s = float(m.group(1)) * {"s": 1, "m": 60, "h": 3600}[m.group(2)]
    return Reservation(
        meta=_meta(doc),
        requests=requests,
        owners=owners,
        allocate_once=bool(spec.get("allocateOnce", True)),
        ttl_s=ttl_s,
        allocate_policy=spec.get("allocatePolicy", ""),
    )


def _device(doc: Mapping) -> Device:
    spec = doc.get("spec") or {}
    infos = []
    for d in spec.get("devices") or []:
        topo = d.get("topology") or {}
        infos.append(
            DeviceInfo(
                dev_type=str(d.get("type", "gpu")).lower(),
                minor=int(d.get("minor", 0)),
                resources=convert_resource_list(d.get("resources") or {}),
                numa_node=int(topo.get("nodeID", -1)),
                pcie_bus=str(topo.get("pcieID", "")),
                vfs=[
                    str(vf.get("busID", ""))
                    for vf in (d.get("vfGroups") or [{}])[0].get("vfs", [])
                ]
                if d.get("vfGroups")
                else [],
            )
        )
    return Device(meta=_meta(doc), devices=infos)


def _elastic_quota(doc: Mapping) -> ElasticQuota:
    meta = _meta(doc)
    spec = doc.get("spec") or {}
    eq = ElasticQuota(
        meta=meta,
        min=convert_resource_list(spec.get("min") or {}),
        max=convert_resource_list(spec.get("max") or {}),
        parent=meta.labels.get(ext.LABEL_QUOTA_PARENT, ""),
        is_parent=meta.labels.get(ext.LABEL_QUOTA_IS_PARENT) == "true",
        tree_id=meta.labels.get(ext.LABEL_QUOTA_TREE_ID, ""),
        is_root=meta.labels.get(ext.LABEL_QUOTA_IS_ROOT) == "true",
    )
    return eq


def _pod_group(doc: Mapping) -> PodGroup:
    spec = doc.get("spec") or {}
    return PodGroup(
        meta=_meta(doc),
        min_member=int(spec.get("minMember", 0)),
    )


def _quota_profile(doc: Mapping) -> ElasticQuotaProfile:
    spec = doc.get("spec") or {}
    return ElasticQuotaProfile(
        meta=_meta(doc),
        quota_name=spec.get("quotaName", ""),
        node_selector={
            str(k): str(v)
            for k, v in (
                (spec.get("nodeSelector") or {}).get("matchLabels") or {}
            ).items()
        },
        quota_labels={
            str(k): str(v)
            for k, v in (spec.get("quotaLabels") or {}).items()
        },
        resource_keys=[str(r) for r in spec.get("resourceKeys") or []],
    )


def _namespace(doc: Mapping) -> NamespaceInfo:
    md = doc.get("metadata") or {}
    return NamespaceInfo(
        name=str(md.get("name", "")),
        labels={str(k): str(v) for k, v in (md.get("labels") or {}).items()},
    )


_CONVERTERS = {
    ("v1", "Pod"): _pod,
    ("v1", "Node"): _node,
    ("v1", "Namespace"): _namespace,
    ("config.koordinator.sh/v1alpha1", "ClusterColocationProfile"): _profile,
    ("scheduling.koordinator.sh/v1alpha1", "Reservation"): _reservation,
    ("scheduling.koordinator.sh/v1alpha1", "Device"): _device,
    ("scheduling.sigs.k8s.io/v1alpha1", "ElasticQuota"): _elastic_quota,
    ("scheduling.sigs.k8s.io/v1alpha1", "PodGroup"): _pod_group,
    ("quota.koordinator.sh/v1alpha1", "ElasticQuotaProfile"): _quota_profile,
}


def load_objects(text: str) -> List[object]:
    """Parse multi-document Koordinator YAML into typed objects.
    Unrecognized (apiVersion, kind) documents are returned as raw dicts so
    callers can dispatch further (e.g. the slo-controller-config
    ConfigMap, third-party kinds like SparkApplication)."""
    import yaml

    out: List[object] = []
    for doc in yaml.safe_load_all(text):
        if not isinstance(doc, dict):
            continue
        key = (str(doc.get("apiVersion", "")), str(doc.get("kind", "")))
        conv = _CONVERTERS.get(key)
        out.append(conv(doc) if conv else doc)
    return out


def load_file(path: str) -> List[object]:
    with open(path) as f:
        return load_objects(f.read())


def load_slo_controller_config(doc: Mapping) -> Optional[Dict]:
    """Extract the slo-controller-config ConfigMap's strategy JSON blobs
    (the dynamic-config channel the nodeslo controller renders from —
    reference ``apis/configuration/slo_controller_config.go``). Returns
    {key: parsed dict} or None when the doc is not that ConfigMap."""
    if not isinstance(doc, Mapping) or doc.get("kind") != "ConfigMap":
        return None
    name = (doc.get("metadata") or {}).get("name", "")
    if name != "slo-controller-config":
        return None
    import json

    out: Dict[str, Dict] = {}
    for key, raw in (doc.get("data") or {}).items():
        try:
            out[str(key)] = json.loads(raw)
        except (ValueError, TypeError):
            continue
    return out
