"""Annotation/label protocol: QoS classes, priority bands, extended resources.

This is the TPU-native rebuild of the reference's ``apis/extension`` package —
the de-facto wire format between components (reference:
``apis/extension/qos.go:23-27`` for QoS classes,
``apis/extension/priority.go:29-48`` for priority bands,
``apis/extension/resource.go:26-28`` for batch/mid extended resources).

Unlike the reference (string annotations parsed per pod per plugin), the rebuild
normalizes the protocol once at snapshot build time into small integer enums so
that the solver works on dense int8/int32 tensors.
"""

from __future__ import annotations

import enum
from typing import Mapping, Optional

DOMAIN = "koordinator.sh"

# --- Labels / annotations (reference: apis/extension/constants.go) ---
LABEL_POD_QOS = f"{DOMAIN}/qosClass"
#: numeric sub-priority within a band (reference constants.go:32)
LABEL_POD_PRIORITY = f"{DOMAIN}/priority"
#: priority band NAME (reference constants.go:36 LabelPodPriorityClass)
LABEL_POD_PRIORITY_CLASS = f"{DOMAIN}/priority-class"
LABEL_QUOTA_NAME = f"quota.scheduling.{DOMAIN}/name"
LABEL_QUOTA_PARENT = f"quota.scheduling.{DOMAIN}/parent"
LABEL_QUOTA_IS_PARENT = f"quota.scheduling.{DOMAIN}/is-parent"
LABEL_QUOTA_TREE_ID = f"quota.scheduling.{DOMAIN}/tree-id"
LABEL_QUOTA_IS_ROOT = f"quota.scheduling.{DOMAIN}/is-root"
LABEL_QUOTA_IGNORE_DEFAULT_TREE = f"quota.scheduling.{DOMAIN}/ignore-default-tree"
LABEL_PREEMPTIBLE = f"quota.scheduling.{DOMAIN}/preemptible"
ANNOTATION_QUOTA_TOTAL_RESOURCE = f"quota.scheduling.{DOMAIN}/total-resource"
#: allow-lent-resource label (quotaNode.AllowLentResource; default true)
LABEL_QUOTA_ALLOW_LENT = f"quota.scheduling.{DOMAIN}/allow-lent-resource"
#: status annotations the quota controller stamps each sync (reference
#: ``elasticquota/controller.go:170-178``)
ANNOTATION_QUOTA_RUNTIME = f"quota.scheduling.{DOMAIN}/runtime"
ANNOTATION_QUOTA_REQUEST = f"quota.scheduling.{DOMAIN}/request"
ANNOTATION_QUOTA_GUARANTEED = f"quota.scheduling.{DOMAIN}/guaranteed"
#: sum of children's requests (leaf: its pod requests) — AnnotationChildRequest
ANNOTATION_QUOTA_CHILD_REQUEST = f"quota.scheduling.{DOMAIN}/child-request"
#: allocated = sum of children's guaranteed (leaf: admitted pod usage) —
#: reference ``elasticquota/core/quota_info.go:62-67``
ANNOTATION_QUOTA_ALLOCATED = f"quota.scheduling.{DOMAIN}/allocated"
#: non-preemptible pods' request/used accounted separately: they must fit
#: inside quota MIN, not runtime (``quota_info.go:49-56``)
ANNOTATION_QUOTA_NON_PREEMPTIBLE_REQUEST = (
    f"quota.scheduling.{DOMAIN}/non-preemptible-request"
)
ANNOTATION_QUOTA_NON_PREEMPTIBLE_USED = (
    f"quota.scheduling.{DOMAIN}/non-preemptible-used"
)
#: namespaces bound to this quota (pods in them default into it) —
#: AnnotationQuotaNamespaces (``elastic_quota.go:52``)
ANNOTATION_QUOTA_NAMESPACES = f"quota.scheduling.{DOMAIN}/namespaces"
#: fair-sharing competition weight as a wire annotation; absent/zero →
#: defaults to max (reference GetSharedWeight, ``elastic_quota.go:95-105``)
ANNOTATION_QUOTA_SHARED_WEIGHT = f"quota.scheduling.{DOMAIN}/shared-weight"
#: bypass the quota webhook's structural guards (LabelAllowForceUpdate)
LABEL_QUOTA_ALLOW_FORCE_UPDATE = f"quota.scheduling.{DOMAIN}/allow-force-update"
#: per-quota admission toggle + declared resource-key set
ANNOTATION_QUOTA_ADMISSION = f"quota.scheduling.{DOMAIN}/admission"
ANNOTATION_QUOTA_RESOURCE_KEYS = f"quota.scheduling.{DOMAIN}/resource-keys"
ANNOTATION_QUOTA_UNSCHEDULABLE_RESOURCE = (
    f"quota.scheduling.{DOMAIN}/unschedulable-resource"
)
ANNOTATION_QUOTA_MAX_STRICT_CHECK_RESOURCE_KEYS = (
    f"quota.scheduling.{DOMAIN}/max-strict-check-resource-keys"
)

#: well-known quota names (reference apis/extension/elastic_quota.go:29-33)
SYSTEM_QUOTA_NAME = "koordinator-system-quota"
ROOT_QUOTA_NAME = "koordinator-root-quota"
DEFAULT_QUOTA_NAME = "koordinator-default-quota"
LABEL_GANG_NAME = "pod-group.scheduling.sigs.k8s.io/name"
LABEL_GANG_MIN_AVAILABLE = "pod-group.scheduling.sigs.k8s.io/min-available"
ANNOTATION_RESOURCE_SPEC = f"scheduling.{DOMAIN}/resource-spec"
ANNOTATION_RESOURCE_STATUS = f"scheduling.{DOMAIN}/resource-status"
ANNOTATION_DEVICE_ALLOCATED = f"scheduling.{DOMAIN}/device-allocated"
#: device-plugin adapter annotations (reference
#: ``pkg/scheduler/plugins/deviceshare/device_plugin_adapter.go``):
#: bind time in unix nanos — device plugins cannot read pod manifests
#: from kubelet, so they disambiguate same-node same-time pods by it
ANNOTATION_BIND_TIMESTAMP = f"scheduling.{DOMAIN}/bind-timestamp"
#: comma-separated allocated GPU minors (env-ref override of
#: NVIDIA_VISIBLE_DEVICES-style image defaults)
ANNOTATION_GPU_MINORS = f"scheduling.{DOMAIN}/gpu-minors"
#: Huawei NPU plugin protocol (vendor-dispatched adapter)
ANNOTATION_PREDICATE_TIME = "predicate-time"
ANNOTATION_HUAWEI_NPU_CORE = "huawei.com/npu-core"
GPU_VENDOR_HUAWEI = "huawei"
LABEL_GPU_VENDOR = f"node.{DOMAIN}/gpu-vendor"
#: ClusterColocationProfile controller opt-in/opt-out
#: (``apis/extension/cluster_colocation_profile.go:24-28``): the
#: controller reconciles a profile only when ReconcileByDefault or this
#: label is "true"; a profile carrying the skip annotation suppresses
#: the webhook's resource mutation for matched pods
LABEL_CONTROLLER_MANAGED = "config.koordinator.sh/controller-managed"
ANNOTATION_SKIP_UPDATE_RESOURCES = "config.koordinator.sh/skip-update-resources"
ANNOTATION_RESERVATION_AFFINITY = f"scheduling.{DOMAIN}/reservation-affinity"
#: smaller non-zero order wins nomination outright (reference
#: ``apis/extension/reservation.go:43-46`` LabelReservationOrder)
LABEL_RESERVATION_ORDER = f"scheduling.{DOMAIN}/reservation-order"
#: "true" = the pod schedules IGNORING reservations entirely (reference
#: ``reservation.go:31-36`` LabelReservationIgnored)
LABEL_RESERVATION_IGNORED = f"scheduling.{DOMAIN}/reservation-ignored"
#: stamped on an owner pod recording WHICH reservation it allocated from
#: (``reservation.go:48-49`` AnnotationReservationAllocated, written at
#: PreBind by SetReservationAllocated)
ANNOTATION_RESERVATION_ALLOCATED = f"scheduling.{DOMAIN}/reservation-allocated"


def is_reservation_ignored(pod) -> bool:
    """reference ``reservation.go:97-99`` IsReservationIgnored."""
    return pod.meta.labels.get(LABEL_RESERVATION_IGNORED) == "true"


#: per-pod PreemptionPolicy override (reference
#: ``apis/extension/preemption.go:22-41`` LabelPodPreemptionPolicy):
#: "Never" = this pod never triggers preemption of other pods
LABEL_POD_PREEMPTION_POLICY = f"scheduling.{DOMAIN}/preemption-policy"
PREEMPTION_POLICY_NEVER = "Never"


def pod_never_preempts(pod) -> bool:
    """Whether the pod's preemption policy forbids preempting on its
    behalf (GetPodKoordPreemptionPolicy == Never)."""
    return (
        pod.meta.labels.get(LABEL_POD_PREEMPTION_POLICY)
        == PREEMPTION_POLICY_NEVER
    )


#: reservation-side options narrowing WHICH resources the Restricted
#: allocate policy binds (reference ``reservation.go:54-55,89-96``
#: AnnotationReservationRestrictedOptions; default = every reserved dim)
ANNOTATION_RESERVATION_RESTRICTED_OPTIONS = (
    f"scheduling.{DOMAIN}/reservation-restricted-options"
)


def parse_reservation_restricted_resources(
    annotations: Mapping[str, str],
) -> Optional[list]:
    """The restricted-options resources list, or None when absent/illegal
    (GetReservationRestrictedOptions)."""
    raw = annotations.get(ANNOTATION_RESERVATION_RESTRICTED_OPTIONS)
    if not raw:
        return None
    import json

    try:
        payload = json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(payload, dict):
        return None
    resources = payload.get("resources")
    if not isinstance(resources, list):
        return None
    return [str(r) for r in resources]


#: pod-side spec restricting nomination to reservations whose allocatable
#: EXACTLY equals the pod's request on the listed resource names
#: (reference ``reservation.go:188-241`` AnnotationExactMatchReservationSpec)
ANNOTATION_EXACT_MATCH_RESERVATION_SPEC = (
    f"scheduling.{DOMAIN}/exact-match-reservation"
)


def parse_exact_match_reservation_spec(
    annotations: Mapping[str, str],
) -> Optional[list]:
    """The spec's resourceNames list, or None when absent/illegal
    (GetExactMatchReservationSpec)."""
    raw = annotations.get(ANNOTATION_EXACT_MATCH_RESERVATION_SPEC)
    if not raw:
        return None
    import json

    try:
        payload = json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(payload, dict):
        return None
    names = payload.get("resourceNames")
    if not isinstance(names, list):
        return None
    return [str(n) for n in names]


def exact_match_reservation(
    pod_requests: Mapping[str, float],
    reservation_allocatable: Mapping[str, float],
    names: Optional[list],
) -> bool:
    """Reference ``ExactMatchReservation`` (reservation.go:222-241),
    including its quirk: a listed name absent on BOTH sides returns
    matched for the WHOLE spec immediately; absent on one side only is
    a mismatch; present on both must compare exactly equal."""
    if not names:
        return True
    for name in names:
        in_r = name in reservation_allocatable
        in_p = name in pod_requests
        if not in_r or not in_p:
            return (not in_r) and (not in_p)
        if float(reservation_allocatable[name]) != float(pod_requests[name]):
            return False
    return True


#: per-pod estimator scaling-factor override in percent per resource name
#: (reference ``apis/extension/load_aware.go:31-32``
#: AnnotationCustomEstimatedScalingFactors, e.g. '{"cpu": 100}')
ANNOTATION_CUSTOM_ESTIMATED_SCALING_FACTORS = (
    f"scheduling.{DOMAIN}/load-estimated-scaling-factors"
)


def parse_custom_estimated_scaling_factors(
    annotations: Mapping[str, str],
) -> Optional[Mapping[str, float]]:
    """{resource: percent} from the pod annotation, or None
    (``load_aware.go:74-82`` GetCustomEstimatedScalingFactors)."""
    raw = annotations.get(ANNOTATION_CUSTOM_ESTIMATED_SCALING_FACTORS)
    if not raw:
        return None
    import json

    try:
        payload = json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(payload, dict):
        return None
    out = {}
    for k, v in payload.items():
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out or None
ANNOTATION_GANG_GROUPS = f"gang.scheduling.{DOMAIN}/groups"
#: which member states count toward gang satisfaction (reference
#: ``apis/extension/coscheduling.go:55-64``); default once-satisfied
ANNOTATION_GANG_MATCH_POLICY = f"gang.scheduling.{DOMAIN}/match-policy"
ANNOTATION_ALIAS_GANG_MATCH_POLICY = "pod-group.scheduling.sigs.k8s.io/match-policy"
GANG_MATCH_ONLY_WAITING = "only-waiting"
GANG_MATCH_WAITING_AND_RUNNING = "waiting-and-running"
GANG_MATCH_ONCE_SATISFIED = "once-satisfied"
#: gang failure handling (reference ``apis/extension/coscheduling.go:40-53``
#: AnnotationGangMode): Strict rolls back the whole gang group on a member
#: failure; NonStrict keeps successfully-placed members
ANNOTATION_GANG_MODE = f"gang.scheduling.{DOMAIN}/mode"
GANG_MODE_STRICT = "Strict"
GANG_MODE_NONSTRICT = "NonStrict"
#: the koordinator-native gang annotation protocol (AnnotationGangPrefix,
#: ``apis/extension/coscheduling.go:25-47``) — takes precedence over the
#: deprecated lightweight-coscheduling labels below it
ANNOTATION_GANG_NAME = f"gang.scheduling.{DOMAIN}/name"
ANNOTATION_GANG_MIN_AVAILABLE = f"gang.scheduling.{DOMAIN}/min-available"
ANNOTATION_GANG_TOTAL_NUM = f"gang.scheduling.{DOMAIN}/total-number"
ANNOTATION_GANG_WAIT_TIME = f"gang.scheduling.{DOMAIN}/waiting-time"
#: stamped BY the scheduler on gang members when the gang times out at
#: Permit (AnnotationGangTimeout, coscheduling.go:48-50)
ANNOTATION_GANG_TIMEOUT = f"gang.scheduling.{DOMAIN}/timeout"


def gang_name_of(pod) -> Optional[str]:
    """Gang name: native annotation first (reference GetGangName), the
    deprecated lightweight label as fallback."""
    return pod.meta.annotations.get(ANNOTATION_GANG_NAME) or pod.meta.labels.get(
        LABEL_GANG_NAME
    )


def gang_min_available_of(pod) -> Optional[int]:
    """minMember: native annotation (GetGangMinNumFromPod) first, the
    lightweight label second; None when absent/unparseable."""
    raw = pod.meta.annotations.get(
        ANNOTATION_GANG_MIN_AVAILABLE
    ) or pod.meta.labels.get(LABEL_GANG_MIN_AVAILABLE)
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def parse_duration_s(raw: Optional[str]) -> Optional[float]:
    """Go time.ParseDuration subset (h/m/s/ms components, e.g. "1h30m",
    "90s"); None on absent/illegal/non-positive — callers fall back to
    their default (gang.go:148-153 waitTime handling)."""
    if not raw:
        return None
    import re

    m = re.fullmatch(
        r"(?:(\d+(?:\.\d+)?)h)?(?:(\d+(?:\.\d+)?)m)?"
        r"(?:(\d+(?:\.\d+)?)s)?(?:(\d+(?:\.\d+)?)ms)?",
        raw.strip(),
    )
    if m is None or not any(m.groups()):
        return None
    h, mi, s, ms = (float(g) if g else 0.0 for g in m.groups())
    total = h * 3600.0 + mi * 60.0 + s + ms / 1000.0
    return total if total > 0 else None


def gang_mode_of(annotations: Mapping[str, str]) -> str:
    """Gang mode from annotations; any illegal value degrades to Strict
    (reference ``coscheduling/core/gang.go:128-132``)."""
    mode = annotations.get(ANNOTATION_GANG_MODE)
    if mode == GANG_MODE_NONSTRICT:
        return GANG_MODE_NONSTRICT
    return GANG_MODE_STRICT
#: pod-side partition request (apis/extension/device_share.go:38
#: AnnotationGPUPartitionSpec): {"allocatePolicy": "Restricted"|"BestEffort",
#: "ringBusBandwidth": <GB/s>}
ANNOTATION_GPU_PARTITION_SPEC = f"scheduling.{DOMAIN}/gpu-partition-spec"
#: joint multi-device allocation directive (reference
#: ``apis/extension/device_share.go:35-36`` AnnotationDeviceJointAllocate)
ANNOTATION_DEVICE_JOINT_ALLOCATE = f"scheduling.{DOMAIN}/device-joint-allocate"
#: per-device-type allocation hints (``device_share.go:147-190``
#: DeviceAllocateHints): {"rdma": {"allocateStrategy": "ApplyForAll"|
#: "RequestsAsCount", "requiredTopologyScope": "PCIe"|"NUMANode"}}
ANNOTATION_DEVICE_ALLOCATE_HINT = f"scheduling.{DOMAIN}/device-allocate-hint"
DEVICE_ALLOCATE_STRATEGY_APPLY_FOR_ALL = "ApplyForAll"
DEVICE_ALLOCATE_STRATEGY_REQUESTS_AS_COUNT = "RequestsAsCount"


def parse_device_allocate_hints(
    annotations: Mapping[str, str],
) -> Mapping[str, Mapping[str, str]]:
    """{deviceType: hint dict} from the device-allocate-hint annotation;
    empty on absent/illegal (GetDeviceAllocateHints)."""
    raw = annotations.get(ANNOTATION_DEVICE_ALLOCATE_HINT)
    if not raw:
        return {}
    import json

    try:
        payload = json.loads(raw)
    except (ValueError, TypeError):
        return {}
    if not isinstance(payload, dict):
        return {}
    return {
        str(k): v for k, v in payload.items() if isinstance(v, dict)
    }
#: node-side partition table annotation (AnnotationGPUPartitions)
ANNOTATION_GPU_PARTITIONS = f"scheduling.{DOMAIN}/gpu-partitions"
#: node label choosing Honor/Prefer (LabelGPUPartitionPolicy)
LABEL_GPU_PARTITION_POLICY = f"node.{DOMAIN}/gpu-partition-policy"
LABEL_GPU_MODEL = f"node.{DOMAIN}/gpu-model"
ANNOTATION_NODE_CPU_TOPOLOGY = f"node.{DOMAIN}/cpu-topology"
#: LS/K8s-Burstable CPU shared pools per NUMA node, computed by the
#: koordlet from the topology minus every cpuset-bound pod's CPUs
#: (reference ``apis/extension/numa_aware.go:46-48``,
#: ``states_noderesourcetopology.go`` calCPUSharePools)
ANNOTATION_NODE_CPU_SHARED_POOLS = f"node.{DOMAIN}/cpu-shared-pools"
#: BE/K8s-BestEffort shared pools: like the LS pools but only LSE pods'
#: CPUs are carved out (BE may ride LSR cores, never LSE cores)
ANNOTATION_NODE_BE_CPU_SHARED_POOLS = f"node.{DOMAIN}/be-cpu-shared-pools"
#: kubelet cpu-manager policy/options/reservedCPUs read back from the
#: kubelet state file (AnnotationKubeletCPUManagerPolicy)
ANNOTATION_KUBELET_CPU_MANAGER_POLICY = "kubelet.koordinator.sh/cpu-manager-policy"
#: K8s Guaranteed pods' kubelet-static cpusets (AnnotationNodeCPUAllocs):
#: the scheduler must not double-allocate these CPUs
ANNOTATION_NODE_CPU_ALLOCS = f"node.{DOMAIN}/pod-cpu-allocs"
#: node-level bind-policy constraint (LabelNodeCPUBindPolicy):
#: FullPCPUsOnly forces whole physical cores for every cpuset pod
LABEL_NODE_CPU_BIND_POLICY = f"node.{DOMAIN}/cpu-bind-policy"
NODE_CPU_BIND_POLICY_FULL_PCPUS_ONLY = "FullPCPUsOnly"
NODE_CPU_BIND_POLICY_SPREAD_BY_PCPUS = "SpreadByPCPUs"
#: node-level NUMA allocate strategy (LabelNodeNUMAAllocateStrategy):
#: MostAllocated = bin-pack zones, LeastAllocated = spread (the plugin
#: default unless the scoring strategy is MostAllocated — reference
#: GetDefaultNUMAAllocateStrategy, nodenumaresource/util.go:33-39)
LABEL_NODE_NUMA_ALLOCATE_STRATEGY = f"node.{DOMAIN}/numa-allocate-strategy"
NODE_NUMA_STRATEGY_MOST_ALLOCATED = "MostAllocated"
NODE_NUMA_STRATEGY_LEAST_ALLOCATED = "LeastAllocated"
#: pod-level NUMA requirement API (AnnotationNUMATopologySpec,
#: ``numa_aware.go:29-31``): the pod's own topology policy (+ exclusive
#: preference), overriding the node's label for this pod's admission
ANNOTATION_NUMA_TOPOLOGY_SPEC = f"scheduling.{DOMAIN}/numa-topology-spec"
#: SYSTEM-QoS cpuset carve-out (AnnotationNodeSystemQOSResource)
ANNOTATION_NODE_SYSTEM_QOS_RESOURCE = f"node.{DOMAIN}/system-qos-resource"
#: total node network bandwidth in bps (AnnotationNodeBandwidth)
ANNOTATION_NODE_BANDWIDTH = f"node.{DOMAIN}/network-bandwidth"
#: batch requests/limits per container, stamped by the pod mutating
#: webhook so CRI-side consumers (runtime proxy, koordlet hooks) see the
#: original extended-resource spec (AnnotationExtendedResourceSpec,
#: ``apis/extension/resource.go:33-36``)
ANNOTATION_EXTENDED_RESOURCE_SPEC = f"node.{DOMAIN}/extended-resource-spec"
#: pods opting into in-place mutating updates (LabelPodMutatingUpdate)
LABEL_POD_MUTATING_UPDATE = f"pod.{DOMAIN}/mutating-update"
ANNOTATION_NODE_RAW_ALLOCATABLE = f"node.{DOMAIN}/raw-allocatable"
ANNOTATION_NODE_AMPLIFICATION = f"node.{DOMAIN}/resource-amplification-ratio"
ANNOTATION_NETWORK_QOS = f"{DOMAIN}/networkQOS"
ANNOTATION_NODE_CPU_NORMALIZATION = f"node.{DOMAIN}/cpu-normalization-ratio"


class QoSClass(enum.IntEnum):
    """Koordinator QoS classes (reference ``apis/extension/qos.go:23-27``).

    Encoded as small ints so pod QoS is a dense int8 column in the snapshot.
    Order encodes strictness: SYSTEM > LSE > LSR > LS > BE > NONE.
    """

    NONE = 0
    BE = 1       # best effort, runs on batch-* overcommitted resources
    LS = 2       # latency sensitive (shared cpus)
    LSR = 3      # latency sensitive reserved (exclusive cpuset)
    LSE = 4      # latency sensitive exclusive (no BE sharing at all)
    SYSTEM = 5

    @classmethod
    def parse(cls, value: Optional[str]) -> "QoSClass":
        if not value:
            return cls.NONE
        try:
            return cls[value.upper()]
        except KeyError:
            return cls.NONE


class PriorityClass(enum.IntEnum):
    """Koord priority bands (reference ``apis/extension/priority.go:29-48``)."""

    NONE = 0
    FREE = 1     # 3000-3999
    BATCH = 2    # 5000-5999
    MID = 3      # 7000-7999
    PROD = 4     # 9000-9999

    @classmethod
    def from_priority(cls, priority: Optional[int]) -> "PriorityClass":
        """Map a k8s pod priority value to a koord priority band.

        Mirrors ``apis/extension/priority.go`` ``GetPodPriorityClassByPriority``:
        inclusive band boundaries, anything outside the bands is NONE.
        """
        if priority is None:
            return cls.NONE
        if 9000 <= priority <= 9999:
            return cls.PROD
        if 7000 <= priority <= 7999:
            return cls.MID
        if 5000 <= priority <= 5999:
            return cls.BATCH
        if 3000 <= priority <= 3999:
            return cls.FREE
        return cls.NONE


PRIORITY_BANDS: Mapping[PriorityClass, tuple[int, int]] = {
    PriorityClass.PROD: (9000, 9999),
    PriorityClass.MID: (7000, 7999),
    PriorityClass.BATCH: (5000, 5999),
    PriorityClass.FREE: (3000, 3999),
}

# --- Resource names (reference: apis/extension/resource.go:26-28) ---
RES_CPU = "cpu"                      # milli-cores
RES_MEMORY = "memory"                # MiB in the snapshot (bytes on the wire)
RES_BATCH_CPU = "kubernetes.io/batch-cpu"
RES_BATCH_MEMORY = "kubernetes.io/batch-memory"
RES_MID_CPU = "kubernetes.io/mid-cpu"
RES_MID_MEMORY = "kubernetes.io/mid-memory"
RES_GPU = "nvidia.com/gpu"           # whole GPU count (integer)
RES_GPU_CORE = f"{DOMAIN}/gpu-core"
RES_GPU_MEMORY = f"{DOMAIN}/gpu-memory"
RES_GPU_MEMORY_RATIO = f"{DOMAIN}/gpu-memory-ratio"
RES_RDMA = f"{DOMAIN}/rdma"
RES_FPGA = f"{DOMAIN}/fpga"
RES_KOORD_GPU = f"{DOMAIN}/gpu"          # percentage-style whole/fractional
RES_GPU_SHARED = f"{DOMAIN}/gpu.shared"  # shared-GPU instance count

#: Canonical dense resource axis for the solver. Extended resources used by a
#: deployment append here; the solver is shape-polymorphic in D.
DEFAULT_RESOURCES = (RES_CPU, RES_MEMORY, RES_BATCH_CPU, RES_BATCH_MEMORY)


def parse_gpu_request(requests: Mapping[str, float]) -> tuple[int, float]:
    """(whole_gpus, share_percent) from a pod's resource requests.

    ``nvidia.com/gpu: k`` → k whole GPUs; ``koordinator.sh/gpu-memory-ratio``
    (or gpu-core) of r → r<100: fraction of one GPU, r≥100: r//100 whole
    plus the remainder (reference ``apis/extension/device_share.go``
    validation rules). This is the *scalar* view the solver lowers; the
    host allocator uses :func:`parse_gpu_request_vector` for independent
    per-dimension accounting.
    """
    whole = int(requests.get(RES_GPU, 0))
    ratio = float(
        requests.get(RES_GPU_MEMORY_RATIO, requests.get(RES_GPU_CORE, 0.0))
    )
    if ratio >= 100.0:
        whole += int(ratio // 100.0)
        ratio = ratio % 100.0
    return whole, ratio


def parse_gpu_request_vector(
    requests: Mapping[str, float],
) -> tuple[int, float, float, Optional[float]]:
    """(whole, core_percent, memory_ratio_percent, memory_bytes|None) —
    the reference's normalized per-dimension GPU request
    (``deviceshare/utils.go:125-200`` request-combination table):

    - ``nvidia.com/gpu: k`` → k whole (core 100 / ratio 100 each)
    - ``koordinator.sh/gpu: r`` → core=r, ratio=r (≥100 splits to whole)
    - ``gpu-core`` + ``gpu-memory-ratio`` → the two dims INDEPENDENTLY
      (a high-memory/low-core pod accounts correctly); equal multiples of
      100 split to whole devices
    - ``gpu-core`` + ``gpu-memory`` (bytes) → core percent + bytes; the
      allocator converts bytes↔ratio per device capacity
    - a single percentage dim alone charges only that dim
    """
    whole = int(requests.get(RES_GPU, 0))
    koord = float(requests.get(RES_KOORD_GPU, 0.0))
    core = float(requests.get(RES_GPU_CORE, 0.0))
    ratio = float(requests.get(RES_GPU_MEMORY_RATIO, 0.0))
    mem_bytes_raw = requests.get(RES_GPU_MEMORY)
    mem_bytes: Optional[float] = (
        float(mem_bytes_raw) if mem_bytes_raw else None
    )
    if koord > 0 and core == 0 and ratio == 0:
        core = ratio = koord
    if core >= 100.0 and core == ratio and core % 100.0 == 0.0:
        whole += int(core // 100.0)
        core = ratio = 0.0
    elif core >= 100.0 and ratio == 0.0 and mem_bytes is None:
        whole += int(core // 100.0)
        core = core % 100.0
        ratio = core
    elif ratio >= 100.0 and core == 0.0:
        whole += int(ratio // 100.0)
        ratio = ratio % 100.0
        core = ratio
    return whole, core, ratio, mem_bytes


def _count_request(requests: Mapping[str, float], key: str) -> int:
    import math

    try:
        raw = float(requests.get(key, 0.0))
    except (TypeError, ValueError):
        return 0
    return int(math.ceil(raw / 100.0)) if raw > 0 else 0


def parse_rdma_request(requests: Mapping[str, float]) -> int:
    """Whole RDMA devices from ``koordinator.sh/rdma`` (the reference
    allocates RDMA NICs in 100-unit instances, ``device_share.go:102``);
    any positive fraction rounds up to a whole device."""
    return _count_request(requests, RES_RDMA)


def parse_fpga_request(requests: Mapping[str, float]) -> int:
    """Whole FPGAs from ``koordinator.sh/fpga`` (``device_share.go:49``,
    same 100-unit instance convention as RDMA)."""
    return _count_request(requests, RES_FPGA)


def should_skip_update_resource(meta) -> bool:
    """``ShouldSkipUpdateResource``
    (``apis/extension/cluster_colocation_profile.go:31-37``): presence of
    the annotation — any value — suppresses the webhook's resource
    mutation for pods matched by this profile."""
    return ANNOTATION_SKIP_UPDATE_RESOURCES in (meta.annotations or {})


def should_reconcile_profile(meta) -> bool:
    """``ShouldReconcileProfile``
    (``cluster_colocation_profile.go:39-41``): the controller reconciles
    a profile only when this label is exactly "true" (or the global
    ReconcileByDefault is on)."""
    return (meta.labels or {}).get(LABEL_CONTROLLER_MANAGED) == "true"


def parse_gpu_partition_table(annotations: Mapping[str, str]):
    """Node-side partition table from the Device CR annotation
    (``GetGPUPartitionTable``, ``device_share.go:354-367``): ``{"<size>":
    [{"minors": [...], "gpuLinkType": ..., "ringBusBandwidth": ...,
    "allocationScore": ...}]}`` → {size: [GPUPartition]}. Returns {} for
    absent/malformed payloads (the allocator then falls back to the
    model-dispatched default table or topology packing)."""
    import json as _json

    from .types import GPUPartition

    raw = annotations.get(ANNOTATION_GPU_PARTITIONS)
    if not raw:
        return {}
    try:
        table = _json.loads(raw)
    except (ValueError, TypeError):
        return {}
    if not isinstance(table, dict):
        return {}
    out = {}
    for size_raw, parts in table.items():
        try:
            size = int(size_raw)
        except (TypeError, ValueError):
            continue
        if not isinstance(parts, list):
            continue
        entries = []
        for p in parts:
            if not isinstance(p, dict):
                continue
            minors = p.get("minors")
            if (
                not isinstance(minors, list)
                or len(minors) != size
                or not all(isinstance(m, int) and m >= 0 for m in minors)
            ):
                # negative minors would crash minors_mask; a size/len
                # mismatch would silently under-allocate
                continue
            try:
                bw = float(p.get("ringBusBandwidth", 0.0) or 0.0)
            except (TypeError, ValueError):
                bw = 0.0
            try:
                score = int(p.get("allocationScore", 1) or 1)
            except (TypeError, ValueError):
                score = 1
            entries.append(
                GPUPartition(
                    minors=minors,
                    link_type=str(p.get("gpuLinkType", "NVLink")),
                    ring_bus_bandwidth=bw,
                    allocation_score=score,
                )
            )
        if entries:
            out[size] = entries
    return out


def gpu_partition_policy(labels: Mapping[str, str]) -> str:
    """Honor iff the node/device label says so; anything else is Prefer
    (``GetGPUPartitionPolicy``, ``device_share.go:369-377``)."""
    return (
        "Honor"
        if labels.get(LABEL_GPU_PARTITION_POLICY) == "Honor"
        else "Prefer"
    )


def parse_device_joint_allocate(
    annotations: Mapping[str, str],
) -> Optional[tuple[tuple[str, ...], str]]:
    """(device_types, required_scope) from the joint-allocate annotation
    (``DeviceJointAllocate``: deviceTypes ordered primary-first;
    requiredScope "SamePCIe" makes PCIe co-location binding)."""
    import json as _json

    raw = annotations.get(ANNOTATION_DEVICE_JOINT_ALLOCATE)
    if not raw:
        return None
    try:
        spec = _json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(spec, dict):
        return None
    types = spec.get("deviceTypes")
    if not isinstance(types, list) or not all(
        isinstance(t, str) for t in types
    ):
        return None
    scope = spec.get("requiredScope")
    return tuple(types), (scope if isinstance(scope, str) else "")


def parse_reservation_affinity(
    annotations: Mapping[str, str],
) -> Optional[Mapping[str, object]]:
    """ReservationAffinity from the pod annotation (reference
    ``apis/extension/reservation.go:51-78``): ``{"name": ...}`` targets one
    reservation directly (other fields ignored); ``{"reservationSelector":
    {labels}}`` requires a matching reservation. Presence means REQUIRED —
    a pod carrying this must allocate from a matching reservation or stay
    unschedulable. A dict with NO recognized field is treated as absent
    — presence gates scheduling behavior (required affinity, preemption
    opt-out), so junk must never read as a requirement."""
    spec = _parse_dict_annotation(annotations, ANNOTATION_RESERVATION_AFFINITY)
    if spec is None:
        return None
    if not any(
        k in spec
        for k in ("name", "reservationSelector", "required", "preferred")
    ):
        return None
    return spec


def parse_gpu_partition_spec(annotations: Mapping[str, str]) -> tuple[bool, float]:
    """(restricted, ring_bus_bandwidth) from the pod's partition-spec
    annotation (``GPUPartitionSpec``: Restricted = only the best
    allocation-score tier may be used; BestEffort = walk down tiers)."""
    spec = _parse_dict_annotation(annotations, ANNOTATION_GPU_PARTITION_SPEC)
    if spec is None:
        return False, 0.0
    try:
        bandwidth = float(spec.get("ringBusBandwidth", 0.0))
    except (TypeError, ValueError):
        bandwidth = 0.0
    return spec.get("allocatePolicy") == "Restricted", bandwidth


#: node-level reserved resources (reference ``node_reservation.go``)
ANNOTATION_NODE_RESERVATION = f"node.{DOMAIN}/reservation"
NODE_RESERVATION_POLICY_DEFAULT = "Default"
NODE_RESERVATION_POLICY_RESERVED_CPUS_ONLY = "ReservedCPUsOnly"
#: per-node LoadAware threshold override (reference ``load_aware.go:30``)
ANNOTATION_CUSTOM_USAGE_THRESHOLDS = f"scheduling.{DOMAIN}/usage-thresholds"
#: per-node colocation overrides (reference ``node_colocation.go``):
#: the annotation carries a ColocationStrategy JSON; the labels override
#: the reclaim ratios with a float in (0, 1]
ANNOTATION_NODE_COLOCATION_STRATEGY = f"node.{DOMAIN}/colocation-strategy"
LABEL_CPU_RECLAIM_RATIO = f"node.{DOMAIN}/cpu-reclaim-ratio"
LABEL_MEMORY_RECLAIM_RATIO = f"node.{DOMAIN}/memory-reclaim-ratio"
#: pods operating as reservations (reference ``operating_pod.go``)
LABEL_POD_OPERATING_MODE = f"scheduling.{DOMAIN}/operating-mode"
POD_OPERATING_MODE_RUNNABLE = "Runnable"
POD_OPERATING_MODE_RESERVATION = "Reservation"
ANNOTATION_RESERVATION_OWNERS = f"scheduling.{DOMAIN}/reservation-owners"
ANNOTATION_RESERVATION_CURRENT_OWNER = (
    f"scheduling.{DOMAIN}/reservation-current-owner"
)
#: reservation-preemption opt-out (reference ``preemption.go:28``)
LABEL_DISABLE_PREEMPTIBLE = f"scheduling.{DOMAIN}/disable-preemptible"
#: descheduling protocol (reference ``apis/extension/descheduling.go``)
ANNOTATION_EVICTION_COST = f"scheduling.{DOMAIN}/eviction-cost"
ANNOTATION_SOFT_EVICTION = f"scheduling.{DOMAIN}/soft-eviction"
EVICTION_COST_MAX = (1 << 31) - 1  # math.MaxInt32 = never evict


def parse_node_reservation(annotations: Mapping[str, str]):
    """NodeReservation from the node annotation (reference
    ``node_reservation.go`` GetNodeReservation): ``{"resources": {...},
    "reservedCPUs": "0-5", "applyPolicy": "Default"}``. None when absent
    or malformed; non-numeric resource values are dropped."""
    spec = _parse_dict_annotation(annotations, ANNOTATION_NODE_RESERVATION)
    if spec is None:
        return None
    resources = spec.get("resources")
    if resources is not None:
        if not isinstance(resources, dict):
            spec = dict(spec)
            spec["resources"] = {}
        else:
            spec = dict(spec)
            spec["resources"] = {
                k: float(v)
                for k, v in resources.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
    if not isinstance(spec.get("reservedCPUs", ""), str):
        spec = dict(spec)
        spec["reservedCPUs"] = ""
    return spec


def parse_custom_usage_thresholds(annotations: Mapping[str, str]):
    """CustomUsageThresholds from the node annotation (reference
    ``load_aware.go`` GetCustomUsageThresholds): per-node REPLACEMENT of
    the LoadAware plugin's usage/prod thresholds (a non-empty custom map
    supersedes the global wholesale — dims absent from it go unchecked).
    None when absent/malformed or when no recognized field is present
    (a truthy junk dict must not read as "custom thresholds exist")."""
    spec = _parse_dict_annotation(
        annotations, ANNOTATION_CUSTOM_USAGE_THRESHOLDS
    )
    if spec is None:
        return None
    if not any(
        k in spec
        for k in (
            "usageThresholds",
            "prodUsageThresholds",
            "aggregatedUsage",
            "usageAggregationType",
        )
    ):
        return None
    return spec


def _parse_json_annotation(annotations: Mapping[str, str], key: str, shape):
    """JSON annotation value of the given shape (dict/list), or None when
    absent/malformed — the shared guard for every structured protocol
    annotation."""
    import json as _json

    raw = annotations.get(key)
    if not raw:
        return None
    try:
        spec = _json.loads(raw)
    except (ValueError, TypeError):
        return None
    return spec if isinstance(spec, shape) else None


def _parse_dict_annotation(annotations: Mapping[str, str], key: str):
    return _parse_json_annotation(annotations, key, dict)


def parse_quota_shared_weight(annotations: Mapping[str, str]):
    """GetSharedWeight (``elastic_quota.go:95-105``): the quota's
    fair-sharing competition weight from its wire annotation. Returns a
    ``{resource: float}`` mapping, or None when the annotation is
    absent, malformed, or all-zero (callers then fall back to the typed
    field and ultimately to max)."""
    spec = _parse_dict_annotation(annotations, ANNOTATION_QUOTA_SHARED_WEIGHT)
    if spec is None:
        return None
    try:
        parsed = {k: float(v) for k, v in spec.items()}
    except (ValueError, TypeError):
        return None
    return parsed if any(v > 0 for v in parsed.values()) else None


def is_reservation_operating_mode(pod) -> bool:
    """IsReservationOperatingMode (``operating_pod.go:52-54``): the pod
    represents a scheduling and resource reservation unit."""
    return (
        pod.meta.labels.get(LABEL_POD_OPERATING_MODE)
        == POD_OPERATING_MODE_RESERVATION
    )


def parse_reservation_owners(annotations: Mapping[str, str]):
    """ReservationOwner list from the reservation-owners annotation
    (``operating_pod.go:70-79`` GetReservationOwners): a JSON list of
    ``{"labelSelector": {"matchLabels": {...}}, "namespace": ...}``.
    Returns [] when absent/malformed."""
    items = _parse_json_annotation(
        annotations, ANNOTATION_RESERVATION_OWNERS, list
    )
    return items if items is not None else []


def is_pod_preemptible(pod) -> bool:
    """IsPodPreemptible (``preemption.go:47-56``): the disable-preemptible
    label opts a pod out of being a preemption victim."""
    return pod.meta.labels.get(LABEL_DISABLE_PREEMPTIBLE) != "true"


def parse_node_colocation_strategy(annotations: Mapping[str, str]):
    """Per-node ColocationStrategy override from the node annotation
    (``node_colocation.go``), or None."""
    return _parse_dict_annotation(
        annotations, ANNOTATION_NODE_COLOCATION_STRATEGY
    )


def parse_reclaim_ratio(labels: Mapping[str, str], key: str):
    """Float reclaim ratio from a node label; None when absent/illegal
    (``node_colocation.go``: the illegal value will be ignored)."""
    raw = labels.get(key)
    if raw is None:
        return None
    try:
        ratio = float(raw)
    except (TypeError, ValueError):
        return None
    return ratio if 0.0 < ratio <= 1.0 else None


def parse_eviction_cost(annotations: Mapping[str, str]) -> int:
    """Eviction cost from the pod annotation (reference
    ``descheduling.go`` GetEvictionCost): implicit 0, negatives allowed,
    MaxInt32 = never evict. Values with a leading plus sign or leading
    zeros are invalid (→ 0), mirroring validFirstDigit."""
    value = annotations.get(ANNOTATION_EVICTION_COST)
    if not value:
        return 0
    first = value[0]
    if not (first == "-" or value == "0" or "1" <= first <= "9"):
        return 0
    try:
        cost = int(value)
    except ValueError:
        return 0
    if cost > EVICTION_COST_MAX or cost < -(1 << 31):
        return 0
    return cost


def parse_node_amplification(annotations: Mapping[str, str]) -> Mapping[str, float]:
    """Resource → amplification ratio from the node annotation (reference
    ``apis/extension/node_resource_amplification.go``
    ``GetNodeResourceAmplificationRatio``). Wire format is
    ``cpu=1.5,memory=1.2``; malformed entries are skipped."""
    raw = annotations.get(ANNOTATION_NODE_AMPLIFICATION, "")
    out = {}
    for part in filter(None, raw.split(",")):
        key, _, val = part.partition("=")
        if not key:
            continue
        try:
            out[key] = float(val)
        except ValueError:
            continue
    return out


#: QoS classes whose whole-core pods take exclusive cpusets — the single
#: source of truth for the bind predicate in both its scalar and
#: vectorized forms (and the solver's on-device ``_cpu_bind``)
CPU_BIND_QOS = (QoSClass.LSR, QoSClass.LSE)


def wants_cpu_bind(pod) -> bool:
    """Pod takes an exclusive cpuset: LSR/LSE QoS with a positive
    whole-core CPU request (reference ``nodenumaresource/plugin.go:251-313``
    requiredCPUBindPolicy resolution). Shared across the snapshot's
    amplified-CPU accounting and the NUMA manager."""
    if pod.qos not in CPU_BIND_QOS:
        return False
    cpu = pod.spec.requests.get(RES_CPU, 0.0)
    return cpu > 0 and cpu % 1000 == 0


def wants_cpu_bind_rows(qos_rows, cpu_milli_rows):
    """Vectorized :func:`wants_cpu_bind` over lowered arrays
    (``qos_rows`` int QoS values, ``cpu_milli_rows`` CPU requests)."""
    import numpy as _np

    bind = _np.zeros(qos_rows.shape, bool)
    for q in CPU_BIND_QOS:
        bind |= qos_rows == int(q)
    return bind & (cpu_milli_rows > 0) & (_np.mod(cpu_milli_rows, 1000.0) == 0)


def qos_for_priority(prio: PriorityClass) -> QoSClass:
    """Default QoS when unspecified, by priority band (reference
    ``apis/extension/qos.go`` ``GetPodQoSClassByName`` fallback semantics)."""
    if prio in (PriorityClass.BATCH, PriorityClass.FREE):
        return QoSClass.BE
    if prio in (PriorityClass.PROD, PriorityClass.MID):
        return QoSClass.LS
    return QoSClass.NONE


# ---- CPU shared pools / kubelet state / NUMA spec wire accessors ----
# (reference ``apis/extension/numa_aware.go`` GetNodeCPUSharePools /
# GetNodeBECPUSharePools / GetKubeletCPUManagerPolicy /
# GetNUMATopologySpec, ``system_qos.go`` GetSystemQOSResource,
# ``node_qos.go`` GetNodeTotalBandwidth, ``resource.go``
# Get/SetExtendedResourceSpec)


def parse_cpu_shared_pools(annotations: Mapping[str, str], be: bool = False):
    """[{"socket": s, "node": n, "cpuset": "0-3,8"}] — the LS (or BE)
    shared pools the koordlet computed for this node; [] when absent or
    malformed."""
    key = (
        ANNOTATION_NODE_BE_CPU_SHARED_POOLS
        if be
        else ANNOTATION_NODE_CPU_SHARED_POOLS
    )
    pools = _parse_json_annotation(annotations, key, list)
    if pools is None:
        return []
    return [p for p in pools if isinstance(p, dict)]


def format_cpu_shared_pools(pools) -> str:
    import json as _json

    return _json.dumps(pools, separators=(",", ":"))


def parse_kubelet_cpu_manager_policy(annotations: Mapping[str, str]):
    """{"policy": "none"|"static", "options": {..}, "reservedCPUs": ".."}
    (KubeletCPUManagerPolicy); None when unset/malformed."""
    return _parse_dict_annotation(
        annotations, ANNOTATION_KUBELET_CPU_MANAGER_POLICY
    )


def parse_node_cpu_allocs(annotations: Mapping[str, str]):
    """[{"namespace":.., "name":.., "uid":.., "cpuset": ".."}] — kubelet
    static-policy Guaranteed pods' exclusive cpusets (PodCPUAlloc)."""
    allocs = _parse_json_annotation(annotations, ANNOTATION_NODE_CPU_ALLOCS, list)
    if allocs is None:
        return []
    return [a for a in allocs if isinstance(a, dict) and a.get("cpuset")]


def parse_numa_topology_spec(annotations: Mapping[str, str]):
    """Pod-level NUMA requirement (NUMATopologySpec): returns
    {"numaTopologyPolicy": str, "singleNUMANodeExclusive": str} or None
    when the annotation is absent/malformed or carries no recognized
    field."""
    spec = _parse_dict_annotation(annotations, ANNOTATION_NUMA_TOPOLOGY_SPEC)
    if spec is None:
        return None
    if not any(
        k in spec
        for k in ("numaTopologyPolicy", "singleNUMANodeExclusive")
    ):
        return None
    return spec


def parse_system_qos_resource(annotations: Mapping[str, str]):
    """SystemQOSResource {"cpuset": .., "cpusetExclusive": bool}; None
    when unset. Exclusivity defaults True (system_qos.go:35-39)."""
    spec = _parse_dict_annotation(
        annotations, ANNOTATION_NODE_SYSTEM_QOS_RESOURCE
    )
    if spec is None or not spec.get("cpuset"):
        return None
    if "cpusetExclusive" not in spec:
        spec = dict(spec)
        spec["cpusetExclusive"] = True
    return spec


def parse_node_bandwidth(annotations: Mapping[str, str]) -> float:
    """Total node network bandwidth in bps (0 = unset/malformed)."""
    raw = annotations.get(ANNOTATION_NODE_BANDWIDTH)
    if not raw:
        return 0.0
    try:
        return max(float(raw), 0.0)
    except (TypeError, ValueError):
        return 0.0


def parse_extended_resource_spec(annotations: Mapping[str, str]):
    """ExtendedResourceSpec {"containers": {name: {"requests": {..},
    "limits": {..}}}} — the batch requests/limits the mutating webhook
    dumped for CRI-side consumers; {} when absent."""
    spec = _parse_dict_annotation(
        annotations, ANNOTATION_EXTENDED_RESOURCE_SPEC
    )
    if spec is None:
        return {}
    containers = spec.get("containers")
    return containers if isinstance(containers, dict) else {}


def format_extended_resource_spec(containers: Mapping[str, Mapping]) -> str:
    import json as _json

    return _json.dumps({"containers": dict(containers)}, separators=(",", ":"))
