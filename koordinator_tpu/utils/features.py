"""Feature gates: three registries, defaulted, mutable for tests.

Rebuild of ``pkg/features/`` — the reference keeps separate gate
registries for the manager/webhook (``features.go:28-139``), koordlet
(``koordlet_features.go:33-162``) and scheduler extras
(``scheduler_features.go:32-53``). Gate names and defaults mirror the
reference; components query their registry at decision points (e.g.
``EnableQuotaAdmission`` gates the quota admission evaluator,
``BECPUSuppress`` the qosmanager strategy).
"""

from __future__ import annotations

import contextlib
from typing import Dict, Iterator, Mapping


class FeatureGate:
    """One registry: known gates with defaults + runtime overrides."""

    def __init__(self, defaults: Mapping[str, bool]):
        self._defaults = dict(defaults)
        self._overrides: Dict[str, bool] = {}

    def enabled(self, feature: str) -> bool:
        if feature in self._overrides:
            return self._overrides[feature]
        if feature not in self._defaults:
            raise KeyError(f"unknown feature gate {feature!r}")
        return self._defaults[feature]

    def set(self, feature: str, value: bool) -> None:
        if feature not in self._defaults:
            raise KeyError(f"unknown feature gate {feature!r}")
        self._overrides[feature] = value

    def set_from_map(self, overrides: Mapping[str, bool]) -> None:
        """componentconfig ``--feature-gates`` ingestion."""
        for feature, value in overrides.items():
            self.set(feature, value)

    def known(self) -> Dict[str, bool]:
        out = dict(self._defaults)
        out.update(self._overrides)
        return out

    @contextlib.contextmanager
    def override(self, feature: str, value: bool) -> Iterator[None]:
        """Test helper (the reference's featuregatetesting.SetFeatureGateDuringTest)."""
        had = feature in self._overrides
        old = self._overrides.get(feature)
        self.set(feature, value)
        try:
            yield
        finally:
            if had:
                self._overrides[feature] = old  # type: ignore[assignment]
            else:
                del self._overrides[feature]


#: manager/webhook gates (reference features.go:28-139)
MANAGER_GATES = FeatureGate(
    {
        "PodMutatingWebhook": True,
        "PodValidatingWebhook": True,
        "ElasticMutatingWebhook": True,
        "ElasticValidatingWebhook": True,
        "NodeMutatingWebhook": False,
        "NodeValidatingWebhook": False,
        "ConfigMapValidatingWebhook": False,
        "ReservationMutatingWebhook": False,
        "ColocationProfileSkipMutatingResources": False,
        "WebhookFramework": True,
        "MultiQuotaTree": False,
        "ElasticQuotaGuaranteeUsage": False,
        "DisableDefaultQuota": False,
        "SupportParentQuotaSubmitPod": False,
        "EnableQuotaAdmission": False,
        "EnableSyncGPUSharedResource": False,
        "ColocationProfileController": False,
        "ValidatePodDeviceResource": False,
    }
)

#: koordlet gates (reference koordlet_features.go:33-162)
KOORDLET_GATES = FeatureGate(
    {
        "AuditEvents": False,
        "AuditEventsHTTPHandler": False,
        "BECPUSuppress": True,
        "BECPUManager": False,
        "BECPUEvict": False,
        "BEMemoryEvict": False,
        "CPUBurst": False,
        "SystemConfig": False,
        "RdtResctrl": True,
        "CgroupReconcile": False,
        "NodeTopologyReport": True,
        "Accelerators": False,
        "RDMADevices": False,
        "CPICollector": False,
        "PSICollector": False,
        "ResctrlCollector": False,
        "BlkIOReconcile": False,
        "ColdPageCollector": False,
        "PodResourcesProxy": False,
    }
)

#: scheduler extra gates (reference scheduler_features.go:32-53)
SCHEDULER_GATES = FeatureGate(
    {
        "MultiQuotaTree": False,
        "ElasticQuotaIgnorePodOverhead": False,
        "ElasticQuotaIgnoreTerminatingPod": False,
        "ElasticQuotaGuaranteeUsage": False,
        "DisableDefaultQuota": False,
        "SupportParentQuotaSubmitPod": False,
        "ResizePod": False,
        "LazyReservationRestore": False,
        "OmitNodeLabelsForReservation": False,
        "DisablePVCReservation": False,
        "PriorityTransformer": False,
        "PreemptionPolicyTransformer": False,
        "DevicePluginAdaption": False,
    }
)
