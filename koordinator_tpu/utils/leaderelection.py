"""Lease-based leader election for the control-plane daemons.

The reference's scheduler/manager/descheduler all gate their loops behind
client-go leader election with a Lease lock
(``cmd/koord-scheduler/app/server.go:247-281``,
``cmd/koord-manager/main.go`` ``LeaderElection`` options). This is the same
state machine — acquire by CAS on a lease record, renew within the renew
deadline, surrender on failure — over a pluggable lock so a single-process
simulation (in-memory) and a multi-process deployment (atomic file lock)
both work without an apiserver.

Defaults mirror client-go: 15 s lease, 10 s renew deadline, 2 s retry.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Optional, Protocol

LEASE_DURATION_S = 15.0
RENEW_DEADLINE_S = 10.0
RETRY_PERIOD_S = 2.0


@dataclasses.dataclass
class LeaseRecord:
    """The contended record (client-go LeaderElectionRecord).

    ``epoch`` is the **fencing token** (HA PR): it increments on every
    leadership *grant* — create, takeover of an expired lease, or
    re-acquisition of one's own lapsed lease — and is preserved across
    renews. Downstream commit/channel boundaries compare a worker's held
    epoch against the current grant, so a deposed leader's in-flight
    writes are rejected instead of double-applied (the Chubby/ZooKeeper
    sequencer discipline client-go leaves to the caller)."""

    holder: str
    acquire_time: float
    renew_time: float
    lease_duration: float
    transitions: int = 0
    epoch: int = 0

    def expired(self, now: float, slack: float = 0.0) -> bool:
        """``slack`` widens the expiry window (clock-skew tolerance): a
        contender waiting ``slack`` extra seconds never steals a lease
        whose holder's clock runs up to ``slack`` ahead of ours."""
        return now - self.renew_time > self.lease_duration + slack


class LeaseLock(Protocol):
    """CAS storage for one LeaseRecord."""

    def get(self) -> Optional[LeaseRecord]: ...

    def create(self, record: LeaseRecord) -> bool: ...

    def update(self, old: LeaseRecord, new: LeaseRecord) -> bool: ...


class InMemoryLeaseLock:
    """Single-process lock — multiple elector instances (threads) contend."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._record: Optional[LeaseRecord] = None

    def get(self) -> Optional[LeaseRecord]:
        with self._lock:
            return dataclasses.replace(self._record) if self._record else None

    def create(self, record: LeaseRecord) -> bool:
        with self._lock:
            if self._record is not None:
                return False
            self._record = dataclasses.replace(record)
            return True

    def update(self, old: LeaseRecord, new: LeaseRecord) -> bool:
        with self._lock:
            cur = self._record
            if cur is None or (cur.holder, cur.renew_time) != (
                old.holder,
                old.renew_time,
            ):
                return False
            self._record = dataclasses.replace(new)
            return True


class FileLeaseLock:
    """Cross-process lock: JSON record + atomic rename, with the
    read-modify-write made a real CAS by a kernel advisory lock
    (``flock``) on a guard file — held only for the microseconds of the
    CAS, released automatically if the holder dies."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._guard = path + ".lock"

    def _with_guard(self, fn):
        import fcntl

        fd = os.open(self._guard, os.O_CREAT | os.O_WRONLY)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            return fn()
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _read(self) -> Optional[LeaseRecord]:
        try:
            with open(self.path) as f:
                return LeaseRecord(**json.load(f))
        except (FileNotFoundError, json.JSONDecodeError, TypeError):
            return None

    def _write(self, record: LeaseRecord) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dataclasses.asdict(record), f)
        os.replace(tmp, self.path)

    def get(self) -> Optional[LeaseRecord]:
        return self._read()

    def create(self, record: LeaseRecord) -> bool:
        def op():
            if self._read() is not None:
                return False
            self._write(record)
            return True

        return self._with_guard(op)

    def update(self, old: LeaseRecord, new: LeaseRecord) -> bool:
        def op():
            cur = self._read()
            if cur is None or (cur.holder, cur.renew_time) != (
                old.holder,
                old.renew_time,
            ):
                return False
            self._write(new)
            return True

        return self._with_guard(op)


class LeaderElector:
    """client-go LeaderElector state machine with injectable clock/sleep."""

    def __init__(
        self,
        lock: LeaseLock,
        identity: str,
        lease_duration: float = LEASE_DURATION_S,
        renew_deadline: float = RENEW_DEADLINE_S,
        retry_period: float = RETRY_PERIOD_S,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        # wall clock, like client-go: lease files persisted across a
        # reboot must still expire (monotonic restarts near 0 at boot)
        now_fn: Callable[[], float] = time.time,
        sleep_fn: Callable[[float], None] = time.sleep,
        clock_skew_s: float = 0.0,
    ) -> None:
        if renew_deadline >= lease_duration:
            raise ValueError("renew_deadline must be < lease_duration")
        self.lock = lock
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        #: extra seconds a FOREIGN lease must be expired before takeover —
        #: tolerates the holder's wall clock running ahead of ours (the
        #: wall-clock analog of client-go's "leases are renewed by
        #: duration, compared by local observation" note)
        self.clock_skew_s = max(0.0, clock_skew_s)
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._now = now_fn
        self._sleep = sleep_fn
        self._observed: Optional[LeaseRecord] = None

    # ---- single protocol step (unit-testable) ----

    def try_acquire_or_renew(self) -> bool:
        now = self._now()
        mine = LeaseRecord(
            holder=self.identity,
            acquire_time=now,
            renew_time=now,
            lease_duration=self.lease_duration,
        )
        cur = self.lock.get()
        if cur is None:
            mine.epoch = 1
            if self.lock.create(mine):
                self._observed = mine
                return True
            return False
        if cur.holder != self.identity:
            if not cur.expired(now, self.clock_skew_s):
                self._observed = cur
                return False
            # expired foreign lease: take over under a NEW fencing epoch
            mine.transitions = cur.transitions + 1
            mine.epoch = cur.epoch + 1
            if self.lock.update(cur, mine):
                self._observed = mine
                return True
            return False
        if cur.expired(now):
            # our own lease lapsed (force-release, or a pause past the
            # lease duration): this is a RE-ACQUISITION, not a renew —
            # the old fencing token must die with the lapse, because a
            # contender may have legitimately treated the lease as free
            mine.transitions = cur.transitions + 1
            mine.epoch = cur.epoch + 1
            if self.lock.update(cur, mine):
                self._observed = mine
                return True
            return False
        # we hold it: renew, preserving acquire time and epoch
        mine.acquire_time = cur.acquire_time
        mine.transitions = cur.transitions
        mine.epoch = cur.epoch
        if self.lock.update(cur, mine):
            self._observed = mine
            return True
        return False

    def is_leader(self) -> bool:
        return (
            self._observed is not None and self._observed.holder == self.identity
        )

    def current_epoch(self) -> Optional[int]:
        """The fencing epoch of the lease we hold (None when not
        leader). This is the token every guarded boundary must carry."""
        return self._observed.epoch if self.is_leader() else None

    def leader_identity(self) -> Optional[str]:
        cur = self.lock.get()
        return cur.holder if cur and not cur.expired(self._now()) else None

    # ---- run loops ----

    def acquire(self, stop: Optional[threading.Event] = None) -> bool:
        """Block until leadership is acquired (or stop is set)."""
        while stop is None or not stop.is_set():
            if self.try_acquire_or_renew():
                if self.on_started_leading:
                    self.on_started_leading()
                return True
            self._sleep(self.retry_period)
        return False

    def renew_loop(self, stop: Optional[threading.Event] = None) -> None:
        """Renew until the renew deadline is blown or stop is set; fires
        on_stopped_leading when leadership is lost."""
        deadline = self._now() + self.renew_deadline
        while stop is None or not stop.is_set():
            if self.try_acquire_or_renew():
                deadline = self._now() + self.renew_deadline
            elif self._now() > deadline:
                break
            self._sleep(self.retry_period)
        self._observed = None
        if self.on_stopped_leading:
            self.on_stopped_leading()

    def release(self) -> None:
        """Voluntarily drop the lease (client-go ReleaseOnCancel)."""
        cur = self.lock.get()
        if cur and cur.holder == self.identity:
            ended = dataclasses.replace(
                cur, renew_time=self._now() - 2 * self.lease_duration
            )
            self.lock.update(cur, ended)
        self._observed = None

    def run(self, stop: Optional[threading.Event] = None) -> None:
        """acquire → renew loop → release, the client-go Run shape."""
        if self.acquire(stop):
            try:
                self.renew_loop(stop)
            finally:
                self.release()


# ---------------------------------------------------------------------------
# Multi-standby election (PR 6): rendezvous ranking over named leases
# ---------------------------------------------------------------------------


def rendezvous_score(candidate: str, key: str) -> int:
    """Highest-random-weight (rendezvous) score of ``candidate`` for
    ``key``. Deterministic across processes (no PYTHONHASHSEED
    dependence), so every standby computes the SAME designated successor
    for a freed shard lease without any coordination round."""
    from . import stable_hash

    return stable_hash(f"{candidate}|{key}")


def preferred_candidate(candidates, key: str) -> Optional[str]:
    """The rendezvous winner among ``candidates`` for ``key`` (None when
    no candidates). Ties break lexicographically — identities are
    unique, so the ranking is total and every observer agrees on it.
    This is how N standbys elect takeover owners per shard: each shard
    key ranks the live membership independently, so a dead incarnation's
    shards spread across the survivors instead of dogpiling one."""
    best = None
    best_score = None
    for c in sorted(candidates):
        s = rendezvous_score(c, key)
        if best_score is None or s > best_score:
            best, best_score = c, s
    return best


class LeaseLockSet:
    """Named in-memory lease locks sharing one registry — the per-shard
    lease table of a horizontally partitioned control plane (one
    :class:`InMemoryLeaseLock` per shard, plus member-presence leases).
    A file-backed deployment uses one :class:`FileLeaseLock` per name
    instead; the registry only exists so a simulation's incarnations
    contend on the same objects."""

    def __init__(self) -> None:
        self._locks: dict = {}
        self._guard = threading.Lock()

    def lock(self, name: str) -> InMemoryLeaseLock:
        with self._guard:
            lk = self._locks.get(name)
            if lk is None:
                lk = self._locks[name] = InMemoryLeaseLock()
            return lk

    def names(self):
        with self._guard:
            return sorted(self._locks)
