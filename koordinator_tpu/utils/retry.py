"""Shared retry policy: exponential backoff + jitter + deadline.

One policy object serves every retry site in the package — the gRPC
``SolverClient`` calls, the informer re-list backoff after repeated watch
disconnects, and the koordlet tick loop — so backoff behavior is tuned in
one place and every attempt is visible in ``retry_attempts_total{site}``.

The policy is a frozen value object; per-call state (attempt counter,
deadline clock) lives in :meth:`run` / :meth:`delay_for` so one policy
can be shared across threads. Jitter draws from a caller-supplied
``random.Random`` so tests (and the chaos soak) stay deterministic.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Tuple, Type


#: process-wide jitter source for callers that don't supply an RNG
_MODULE_RNG = random.Random()


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: ``base * multiplier**attempt`` capped at
    ``max_delay_s``, ±``jitter`` fraction, bounded by ``max_attempts``
    and an optional overall ``deadline_s``."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    jitter: float = 0.1
    deadline_s: Optional[float] = None

    def delay_for(
        self, attempt: int, rng: Optional[random.Random] = None
    ) -> float:
        """Backoff before retry number ``attempt`` (0-based). The
        exponent is clamped before exponentiation: never-die loops feed
        an unbounded attempt counter, and ``2.0 ** 1075`` would raise
        OverflowError in exactly the loop backoff was meant to keep
        alive (the min() against max_delay_s comes too late)."""
        d = min(
            self.base_delay_s * self.multiplier ** min(max(attempt, 0), 64),
            self.max_delay_s,
        )
        if self.jitter > 0:
            # jitter must apply even when the caller supplies no RNG —
            # identical backoff schedules across a fleet recreate the
            # thundering herd the jitter exists to break (tests pass a
            # seeded rng or jitter=0 for determinism)
            r = rng if rng is not None else _MODULE_RNG
            d *= 1.0 + self.jitter * (2.0 * r.random() - 1.0)
        return d

    def run(
        self,
        fn: Callable,
        retry_on: Tuple[Type[BaseException], ...] = (Exception,),
        site: str = "",
        counter=None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
    ):
        """Call ``fn`` until it succeeds, a non-retryable exception
        escapes, attempts are exhausted, or the deadline would be blown
        by the next backoff. ``counter`` is an optional
        ``retry_attempts_total{site}`` Counter; ``on_retry(attempt,
        exc)`` observes each retry decision."""
        start = clock()
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as exc:
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                delay = self.delay_for(attempt - 1, rng)
                if (
                    self.deadline_s is not None
                    and clock() - start + delay > self.deadline_s
                ):
                    raise
                if counter is not None:
                    counter.labels(site=site).inc()
                if on_retry is not None:
                    on_retry(attempt, exc)
                if delay > 0:
                    sleep(delay)


#: conservative default shared by call sites that don't tune their own
DEFAULT_RETRY = RetryPolicy()
