"""List/watch informers: the rebuild's answer to ``pkg/client``.

The reference generates 6.6k LoC of clientsets/informers/listers per CRD;
the mechanism underneath is small and this module provides it natively:

* :class:`ObjectTracker` — a versioned object store (the apiserver
  analog): every mutation bumps a monotonically increasing resource
  version and fans out watch events to open watches.
* :class:`Informer` — LIST+WATCH with a local cache (the lister), event
  handlers (add/update/delete), periodic full **resync** (re-delivering
  the cache as updates, like shared informers), and automatic **re-list
  on watch failure** — the disconnect-recovery behavior VERDICT r1 noted
  had no counterpart (a dropped watch can never silently diverge a
  consumer's view; compare the gRPC channel's generation-gap protocol in
  ``runtime.snapshot_channel`` for the cross-process path).

Consumers: anything holding derived state — e.g. a ``ClusterSnapshot``
kept in sync by informer handlers instead of direct setters (see
``tests/test_informer.py`` for that composition).
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .retry import RetryPolicy

#: watch event kinds
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclasses.dataclass
class WatchEvent:
    kind: str            # ADDED | MODIFIED | DELETED
    key: str             # namespace/name (or name for cluster-scoped)
    obj: object
    resource_version: int


class WatchClosed(Exception):
    """The watch stream ended (server closed / simulated disconnect)."""


class _Watch:
    """One open watch: a bounded event queue; overflow closes the watch
    (the apiserver does the same — a too-slow watcher must re-list)."""

    def __init__(self, since: int, capacity: int = 1024):
        self.since = since
        self.capacity = capacity
        self.closed = False  # mirror of _closed for the tracker's pruning
        self._events: List[WatchEvent] = []
        self._cond = threading.Condition()
        self._closed = False

    def deliver(self, event: WatchEvent) -> None:
        with self._cond:
            if self._closed:
                return
            if len(self._events) >= self.capacity:
                self._closed = True     # overflow → force re-list
                self.closed = True
                self._events.clear()    # free the backlog immediately
            else:
                self._events.append(event)
            self._cond.notify_all()

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Blocking pop; None on timeout; raises WatchClosed when ended."""
        with self._cond:
            if not self._events and not self._closed:
                self._cond.wait(timeout)
            if self._events:
                return self._events.pop(0)
            if self._closed:
                raise WatchClosed()
            return None

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self.closed = True
            self._cond.notify_all()


class ObjectTracker:
    """Versioned object store + watch fan-out (one per resource kind)."""

    def __init__(self, chaos=None):
        from ..chaos import NULL_INJECTOR

        self._lock = threading.Lock()
        self._objects: Dict[str, Tuple[object, int]] = {}
        self._rv = 0
        self._watches: List[_Watch] = []
        #: fault injector for ``informer.silent_stall`` (gray-failure
        #: containment PR): the tracker is where delivery can go silent
        #: while every watch stays open
        self.chaos = chaos or NULL_INJECTOR

    def _fanout(self, event: WatchEvent) -> None:
        """Deliver under the tracker lock: events reach every watch in
        resource-version order (out-of-order delivery would make the
        consumer's stale-replay check drop a live event), and closed
        watches (overflow / abandoned after a re-list) are pruned here so
        they cannot accumulate."""
        if self.chaos.enabled and self.chaos.fire("informer.silent_stall"):
            # gray failure: the rv advanced, the watches stay OPEN, the
            # event is never delivered — consumers' caches silently
            # freeze with /healthz green. Recovery is a re-list (the
            # suppressed events are gone from the watch stream); the
            # staleness watchdog is what notices the rv gap.
            return
        alive = []
        for w in self._watches:
            w.deliver(event)
            if not w.closed:
                alive.append(w)
        self._watches = alive

    def upsert(self, key: str, obj: object) -> int:
        with self._lock:
            self._rv += 1
            kind = MODIFIED if key in self._objects else ADDED
            self._objects[key] = (obj, self._rv)
            self._fanout(WatchEvent(kind, key, obj, self._rv))
            return self._rv

    def delete(self, key: str) -> Optional[int]:
        with self._lock:
            entry = self._objects.pop(key, None)
            if entry is None:
                return None
            self._rv += 1
            self._fanout(WatchEvent(DELETED, key, entry[0], self._rv))
            return self._rv

    def list(self) -> Tuple[Dict[str, object], int]:
        """(objects, resource_version) — the LIST verb."""
        with self._lock:
            return {k: o for k, (o, _v) in self._objects.items()}, self._rv

    def version(self) -> int:
        """Current resource version — the freshness watchdog's "how far
        the world has moved" side of the lag comparison."""
        with self._lock:
            return self._rv

    def watch(self, since: int) -> _Watch:
        """Open a watch from ``since``; events older than ``since`` are
        NOT replayed (watch caches are bounded) — a too-old ``since``
        surfaces as missed events that only a re-list repairs, exactly
        the failure mode the Informer recovers from. Prefer
        ``list_and_watch`` — a separate LIST + WATCH leaves a gap in
        which events are lost."""
        w = _Watch(since)
        with self._lock:
            self._watches.append(w)
        return w

    def list_and_watch(self) -> Tuple[Dict[str, object], int, _Watch]:
        """Atomic LIST + WATCH: no mutation can land between the snapshot
        and the watch registration (the list-then-watch gap would lose
        that event forever on a quiet stream)."""
        with self._lock:
            objects = {k: o for k, (o, _v) in self._objects.items()}
            w = _Watch(self._rv)
            self._watches.append(w)
            return objects, self._rv, w

    def close_all_watches(self) -> None:
        """Simulate an apiserver disconnect: every open watch ends."""
        with self._lock:
            watches = list(self._watches)
            self._watches.clear()
        for w in watches:
            w.close()


Handler = Callable[[str, object], None]
DeleteHandler = Callable[[str, object], None]


class Informer:
    """LIST+WATCH consumer with a local cache and resync/re-list loops."""

    def __init__(
        self,
        tracker: ObjectTracker,
        resync_interval_s: float = 0.0,
        retry: Optional[RetryPolicy] = None,
        chaos=None,
        health=None,
        name: str = "",
        error_registry=None,
    ):
        from ..chaos import NULL_INJECTOR

        self.tracker = tracker
        self.resync_interval_s = resync_interval_s
        #: re-list backoff after REPEATED disconnects (the first re-list
        #: is immediate — one disconnect is routine; a flapping stream
        #: must not busy-spin LIST against the tracker)
        self.retry = retry or RetryPolicy(
            max_attempts=1 << 30, base_delay_s=0.02, max_delay_s=1.0
        )
        self.chaos = chaos or NULL_INJECTOR
        #: optional obs.HealthRegistry + subsystem name for /healthz
        self.health = health
        self.name = name or f"informer-{id(self):x}"
        self.error_registry = error_registry
        self._cache: Dict[str, object] = {}
        self._rv = 0
        self._lock = threading.Lock()
        #: signalled whenever _rv advances (wait_synced blocks on it
        #: instead of the former 5 ms busy-poll)
        self._rv_cond = threading.Condition(self._lock)
        self._backoff_rng = random.Random(0)
        self._on_add: List[Handler] = []
        self._on_update: List[Handler] = []
        self._on_delete: List[DeleteHandler] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: diagnostics: how many full re-lists ran (1 = initial sync)
        self.relists = 0
        #: consecutive disconnects without a healthy event in between
        self.consecutive_disconnects = 0
        #: total seconds spent backing off before re-lists
        self.backoff_total_s = 0.0
        #: (key, exception) pairs from handlers that raised (isolated)
        self.handler_errors: List[Tuple[str, Exception]] = []

    # ---- handler registration (AddEventHandler) ----

    def add_handlers(
        self,
        on_add: Optional[Handler] = None,
        on_update: Optional[Handler] = None,
        on_delete: Optional[DeleteHandler] = None,
    ) -> None:
        if on_add:
            self._on_add.append(on_add)
        if on_update:
            self._on_update.append(on_update)
        if on_delete:
            self._on_delete.append(on_delete)

    # ---- lister ----

    def get(self, key: str) -> Optional[object]:
        with self._lock:
            return self._cache.get(key)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._cache)

    def observed_rv(self) -> int:
        """The rv every handler has fully observed — the consumer side
        of the staleness watchdog's lag comparison (a tracker rv ahead
        of this for longer than the horizon is a silent stream)."""
        with self._lock:
            return self._rv

    # ---- sync machinery ----

    def _relist(self) -> "_Watch":
        """LIST+WATCH atomically, reconcile the cache against the fresh
        world (deliver adds/updates/deletes for the diff) — the
        shared-informer re-list flow."""
        objects, rv, watch = self.tracker.list_and_watch()
        with self._lock:
            old = dict(self._cache)
            self._cache = dict(objects)
        for key, obj in objects.items():
            if key not in old:
                self._call(self._on_add, key, obj)
            elif old[key] is not obj:
                self._call(self._on_update, key, obj)
        for key, obj in old.items():
            if key not in objects:
                self._call(self._on_delete, key, obj)
        # _rv becomes visible — and wait_synced wakes — only AFTER every
        # handler ran: HasSynced means "consumers observed this state",
        # not "the cache stored it" (a waiter woken between cache write
        # and handler execution would read a consumer still behind)
        with self._rv_cond:
            self._rv = rv
            self._rv_cond.notify_all()
        self.relists += 1
        return watch

    def _call(self, handlers, key, obj) -> None:
        """Handler isolation: one consumer's exception must not kill the
        sync thread and silently freeze every other consumer's view."""
        for h in handlers:
            try:
                h(key, obj)
            except Exception as e:  # noqa: BLE001 — surfaced, not fatal
                from ..obs.errors import report_exception

                report_exception(
                    "informer.handler", e, registry=self.error_registry
                )
                self.handler_errors.append((key, e))

    def _apply(self, event: WatchEvent) -> None:
        if event.resource_version <= self._rv:
            return  # stale replay
        with self._lock:
            if event.kind == DELETED:
                self._cache.pop(event.key, None)
            else:
                self._cache[event.key] = event.obj
        handlers = (
            self._on_delete
            if event.kind == DELETED
            else self._on_add if event.kind == ADDED else self._on_update
        )
        self._call(handlers, event.key, event.obj)
        # advance + notify only after handlers ran (see _relist): a
        # wait_synced waiter woken at this rv must find every consumer
        # already caught up, not mid-handler
        with self._rv_cond:
            self._rv = event.resource_version
            self._rv_cond.notify_all()

    def run(self) -> None:
        """Blocking sync loop: initial list, then watch; any watch end
        (disconnect/overflow) triggers a full re-list. Repeated
        disconnects back off per the shared RetryPolicy (a flapping
        apiserver must not be hammered with LIST storms) and surface as
        a degraded subsystem on the health registry; the chaos point
        ``informer.watch_closed`` severs the live watch on demand."""
        import time

        watch = self._relist()
        last_resync = time.monotonic()
        while not self._stop.is_set():
            if self.chaos.enabled and self.chaos.fire("informer.watch_closed"):
                watch.close()   # injected disconnect: drain to WatchClosed
            try:
                event = watch.next(timeout=0.05)
            except WatchClosed:
                if self._stop.is_set():
                    break
                self.consecutive_disconnects += 1
                if self.consecutive_disconnects >= 2:
                    # first re-list is immediate; a flapping stream backs
                    # off (stop-aware wait so shutdown stays prompt)
                    if self.health is not None:
                        self.health.set(
                            self.name,
                            False,
                            f"{self.consecutive_disconnects} consecutive "
                            "watch disconnects; re-list backing off",
                        )
                    delay = self.retry.delay_for(
                        self.consecutive_disconnects - 2, self._backoff_rng
                    )
                    self.backoff_total_s += delay
                    if self.error_registry is not None:
                        c = self.error_registry.get("retry_attempts_total")
                        if c is None:
                            c = self.error_registry.counter(
                                "retry_attempts_total",
                                "retries performed by shared RetryPolicy "
                                "call sites",
                                labels=("site",),
                            )
                        c.labels(site="informer.relist").inc()
                    if self._stop.wait(delay):
                        break
                self.chaos.fire("informer.relist.delay")
                watch = self._relist()   # informer re-list on disconnect
                continue
            if self.consecutive_disconnects:
                # reaching here — an event OR a quiet poll timeout —
                # proves the re-listed stream is alive again (a quiet
                # tracker never emits events, so recovery must not
                # depend on one arriving)
                self.consecutive_disconnects = 0
                if self.health is not None:
                    self.health.set(self.name, True)
            if event is not None:
                self._apply(event)
            if (
                self.resync_interval_s > 0
                and time.monotonic() - last_resync >= self.resync_interval_s
            ):
                # periodic resync: re-deliver the cached world as updates
                # so level-triggered consumers self-heal
                with self._lock:
                    items = list(self._cache.items())
                for key, obj in items:
                    self._call(self._on_update, key, obj)
                last_resync = time.monotonic()

    def start(self) -> "Informer":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def wait_synced(self, rv: int, timeout: float = 10.0) -> bool:
        """Block until the cache has observed ``rv`` (HasSynced analog).
        Condition-variable wait: wakes exactly when ``_rv`` advances
        (the former 5 ms ``time.sleep`` busy-poll burned a core-slice per
        waiting consumer and added up to 5 ms latency per sync point)."""
        import time

        deadline = time.monotonic() + timeout
        with self._rv_cond:
            while self._rv < rv:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._rv_cond.wait(remaining)
            return True
