"""Prometheus-style metrics registry shared by all components.

Rebuild of the reference's four metrics packages —
``pkg/scheduler/metrics/metrics.go:38-83`` (SchedulingTimeout,
ElasticQuotaProcessLatency, WaitingGangGroupNumber, …),
``pkg/koordlet/metrics/``, ``pkg/descheduler/metrics`` and
``pkg/util/metrics/koordmanager`` — as one small dependency-free registry
with Prometheus text exposition. Components create their own
:class:`Registry` (the reference registers against separate legacy/k8s
registries per binary) and the services engine serves ``/metrics``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple


def _escape_label(value: str) -> str:
    """Prometheus text-exposition label-value escaping."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    """HELP-line escaping (backslash and newline only, per the spec)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _check_labels(label_names: Tuple[str, ...], labels: Mapping[str, str]) -> None:
    """Labels not declared at metric construction are a caller bug —
    silently dropping them used to record into the wrong series."""
    unknown = [k for k in labels if k not in label_names]
    if unknown:
        raise ValueError(
            f"unknown label(s) {unknown!r}; declared label names are "
            f"{list(label_names)!r}"
        )


@dataclass
class _Series:
    value: float = 0.0


class Counter:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._series: Dict[Tuple[str, ...], _Series] = {}
        self._lock = threading.Lock()

    def labels(self, **labels: str) -> "_CounterChild":
        _check_labels(self.label_names, labels)
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            series = self._series.setdefault(key, _Series())
        return _CounterChild(series, self._lock)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def value(self, **labels: str) -> float:
        _check_labels(self.label_names, labels)
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            return self._series.get(key, _Series()).value

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} counter",
        ]
        with self._lock:
            for key, s in sorted(self._series.items()):
                labels = dict(zip(self.label_names, key))
                lines.append(f"{self.name}{_fmt_labels(labels)} {s.value}")
        return lines


class _CounterChild:
    def __init__(self, series: _Series, lock: threading.Lock):
        self._series = series
        self._lock = lock

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._series.value += amount


class Gauge(Counter):
    def labels(self, **labels: str) -> "_GaugeChild":
        _check_labels(self.label_names, labels)
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            series = self._series.setdefault(key, _Series())
        return _GaugeChild(series, self._lock)

    def set(self, value: float, **labels: str) -> None:
        self.labels(**labels).set(value)

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
        ]
        with self._lock:
            for key, s in sorted(self._series.items()):
                labels = dict(zip(self.label_names, key))
                lines.append(f"{self.name}{_fmt_labels(labels)} {s.value}")
        return lines


class _GaugeChild(_CounterChild):
    def set(self, value: float) -> None:
        with self._lock:
            self._series.value = value


DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


@dataclass
class _HistSeries:
    counts: List[int] = field(default_factory=list)
    total: float = 0.0
    n: int = 0


class Histogram:
    def __init__(
        self,
        name: str,
        help_: str,
        label_names: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets)
        self._series: Dict[Tuple[str, ...], _HistSeries] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        _check_labels(self.label_names, labels)
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            s = self._series.setdefault(
                key, _HistSeries(counts=[0] * (len(self.buckets) + 1))
            )
            s.total += value
            s.n += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s.counts[i] += 1
                    break
            else:
                s.counts[-1] += 1

    def quantile(self, q: float, **labels: str) -> float:
        """Approximate quantile from bucket counts, linearly interpolated
        within the winning bucket (Prometheus ``histogram_quantile``
        semantics: the first bucket's lower edge is 0). A target landing
        in the ``+Inf`` overflow bucket stays ``+Inf`` — there is no
        upper edge to interpolate toward."""
        _check_labels(self.label_names, labels)
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            s = self._series.get(key)
            if s is None or s.n == 0:
                return 0.0
            target = q * s.n
            acc = 0
            for i, c in enumerate(s.counts[:-1]):
                if acc + c >= target and c > 0:
                    lower = self.buckets[i - 1] if i > 0 else 0.0
                    upper = self.buckets[i]
                    frac = (target - acc) / c
                    return lower + frac * (upper - lower)
                acc += c
            return float("inf")

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            for key, s in sorted(self._series.items()):
                labels = dict(zip(self.label_names, key))
                acc = 0
                for i, b in enumerate(self.buckets):
                    acc += s.counts[i]
                    le = dict(labels, le=repr(float(b)))
                    lines.append(f"{self.name}_bucket{_fmt_labels(le)} {acc}")
                le = dict(labels, le="+Inf")
                lines.append(f"{self.name}_bucket{_fmt_labels(le)} {s.n}")
                lines.append(f"{self.name}_sum{_fmt_labels(labels)} {s.total}")
                lines.append(f"{self.name}_count{_fmt_labels(labels)} {s.n}")
        return lines


class Registry:
    """Per-component metric registry with text exposition."""

    def __init__(self, namespace: str = ""):
        self.namespace = namespace
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _full(self, name: str) -> str:
        return f"{self.namespace}_{name}" if self.namespace else name

    def counter(self, name: str, help_: str = "", labels: Sequence[str] = ()) -> Counter:
        return self._get(name, Counter, lambda n: Counter(n, help_, labels))

    def gauge(self, name: str, help_: str = "", labels: Sequence[str] = ()) -> Gauge:
        return self._get(name, Gauge, lambda n: Gauge(n, help_, labels))

    def histogram(
        self,
        name: str,
        help_: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get(
            name, Histogram, lambda n: Histogram(n, help_, labels, buckets)
        )

    def _get(self, name, kind, factory):
        full = self._full(name)
        with self._lock:
            m = self._metrics.get(full)
            if m is None:
                m = factory(full)
                self._metrics[full] = m
            elif type(m) is not kind:
                # exact-type check: Gauge subclasses Counter, so isinstance
                # would hand a Gauge to a counter() caller
                raise ValueError(
                    f"metric {full!r} already registered as "
                    f"{type(m).__name__}, requested {kind.__name__}"
                )
            return m

    def get(self, name: str) -> Optional[object]:
        return self._metrics.get(self._full(name))

    def expose(self) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"
