"""Shared utility helpers."""

import hashlib


def stable_hash(key: str) -> int:
    """Process-stable 64-bit hash (blake2b — no PYTHONHASHSEED
    dependence). THE hash for every cross-process-deterministic ranking
    in the partitioned control plane: shard routing
    (``runtime.shards.ShardMap``) and rendezvous election
    (``leaderelection.rendezvous_score``) must agree on one function,
    or determinism guarantees silently diverge."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )
